"""Trainer for pairwise clone detection (the run_clone path).

Role parity with CodeT5/run_clone.py: cross-entropy over 2 classes,
per-epoch dev F1, best-F1 checkpointing, early stopping on F1 patience
(run_clone.py mirrors run_defect.py:398-405). dp sharding is the same
exact-sum shard_map pattern as the other trainers (1-device == N-device).
"""

from __future__ import annotations

import dataclasses
import logging
import time
from functools import partial
from typing import Callable, Iterable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, PartitionSpec as P

from deepdfa_tpu.parallel.compat import shard_map

from deepdfa_tpu.core.config import Config
from deepdfa_tpu.models import t5_gen as gen
from deepdfa_tpu.parallel import sharding
from deepdfa_tpu.parallel.mesh import make_mesh
from deepdfa_tpu.train.metrics import BinaryClassificationMetrics
from deepdfa_tpu.train.state import TrainState, make_optimizer

logger = logging.getLogger(__name__)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class CloneBatch:
    pair_ids: jax.Array  # [B, 2, T] int32 (or [dp, B, 2, T] sharded)
    labels: jax.Array  # [B] int32
    row_mask: jax.Array  # [B] bool


def collate_clone_shards(
    pair_ids: np.ndarray,
    labels: Sequence[int],
    num_shards: int,
    rows_per_shard: int,
    pad_id: int = 0,
) -> CloneBatch:
    n = pair_ids.shape[0]
    if n > num_shards * rows_per_shard:
        raise ValueError(f"{n} rows > {num_shards} x {rows_per_shard}")
    shards = []
    for s in range(num_shards):
        sel = list(range(s, n, num_shards))[:rows_per_shard]
        ids = np.full(
            (rows_per_shard,) + pair_ids.shape[1:], pad_id, np.int32
        )
        lab = np.zeros((rows_per_shard,), np.int32)
        mask = np.zeros((rows_per_shard,), bool)
        ids[: len(sel)] = pair_ids[sel]
        lab[: len(sel)] = np.asarray(labels)[sel]
        mask[: len(sel)] = True
        shards.append(CloneBatch(pair_ids=ids, labels=lab, row_mask=mask))
    return jax.tree.map(lambda *xs: np.stack(xs, axis=0), *shards)


def clone_batches_of(
    pair_ids: np.ndarray,
    labels: Sequence[int],
    num_shards: int,
    rows_per_shard: int,
    pad_id: int = 0,
    shuffle_seed: int | None = None,
) -> list[CloneBatch]:
    n = pair_ids.shape[0]
    order = np.arange(n)
    if shuffle_seed is not None:
        np.random.default_rng(shuffle_seed).shuffle(order)
    labels = np.asarray(labels)
    per = num_shards * rows_per_shard
    return [
        collate_clone_shards(
            pair_ids[order[i : i + per]],
            labels[order[i : i + per]],
            num_shards,
            rows_per_shard,
            pad_id,
        )
        for i in range(0, n, per)
    ]


class CloneTrainer:
    """dp trainer for CloneConfig pairwise classifiers."""

    def __init__(
        self,
        cfg: Config,
        clone_cfg: gen.CloneConfig,
        mesh: Mesh | None = None,
        total_steps: int | None = None,
    ):
        self.cfg = cfg
        self.clone_cfg = clone_cfg
        self.mesh = mesh if mesh is not None else make_mesh(cfg.train.mesh)
        self.tx = make_optimizer(cfg.train.optim, total_steps)
        # unified sharding layer (parallel/sharding.py): replicated on a
        # dp mesh; MeshConfig.rules can reshard declaratively
        self.sharding_map = sharding.sharding_map_for(
            "clone", mesh_shape=dict(self.mesh.shape),
            extra_rules=getattr(cfg.train.mesh, "rules", ()),
        )
        self._build_steps()

    def _place_params(self, params):
        return self.sharding_map.place(self.mesh, params)

    def make_checkpoints(self, directory, monitor="val_f1", mode="max"):
        from deepdfa_tpu.train.checkpoint import CheckpointManager

        return CheckpointManager(directory, monitor=monitor, mode=mode)

    def init_state(self, seed: int | None = None) -> TrainState:
        seed = self.cfg.train.seed if seed is None else seed
        params = gen.init_clone_params(self.clone_cfg, jax.random.key(seed))
        params = self._place_params(params)
        return TrainState.create(params, self.tx)

    def load_params(self, state: TrainState, params) -> TrainState:
        params = self._place_params(jax.device_get(params))
        return TrainState(
            params=params, opt_state=self.tx.init(params), step=state.step
        )

    def load_seq2seq(self, state: TrainState, gen_params) -> TrainState:
        """Warm-start encoder-decoder from a generation checkpoint (or
        gen_params_from_hf_torch output)."""
        params = dict(jax.device_get(state.params))
        s2s = dict(jax.device_get(gen_params))
        s2s["decoder"] = dict(s2s["decoder"])
        # the clone path never uses the LM head
        s2s["decoder"].pop("lm_head", None)
        params["seq2seq"] = s2s
        params = self._place_params(params)
        return TrainState(
            params=params, opt_state=self.tx.init(params), step=state.step
        )

    def _build_steps(self) -> None:
        mesh = self.mesh
        ccfg = self.clone_cfg
        batch_specs = CloneBatch(
            pair_ids=P(("dp",)), labels=P(("dp",)), row_mask=P(("dp",))
        )
        param_specs = jax.tree.map(lambda _: P(), jax.eval_shape(
            lambda: gen.init_clone_params(ccfg, jax.random.key(0))
        ))

        def _loss_sum(params, local: CloneBatch, key):
            logits = gen.clone_forward(
                ccfg, params, local.pair_ids, dropout_key=key
            )
            per = optax.softmax_cross_entropy_with_integer_labels(
                logits, local.labels
            )
            m = local.row_mask.astype(per.dtype)
            return (per * m).sum(), (m.sum(), logits)

        @partial(
            shard_map,
            mesh=mesh,
            in_specs=(param_specs, batch_specs, P()),
            out_specs=(P(), param_specs),
            check_vma=False,
        )
        def _sharded_grads(params, batch, key):
            local = jax.tree.map(lambda x: x[0], batch)
            key = jax.random.fold_in(key, jax.lax.axis_index("dp"))
            count = local.row_mask.sum().astype(jnp.float32)
            count_g = jnp.maximum(jax.lax.psum(count, "dp"), 1.0)

            def fn(p):
                return _loss_sum(p, local, key)[0] / count_g

            loss_local, grads = jax.value_and_grad(fn)(params)
            loss = jax.lax.psum(loss_local, "dp")
            grads = jax.tree.map(lambda g: jax.lax.psum(g, "dp"), grads)
            return loss, grads

        @partial(jax.jit, donate_argnums=0)
        def train_step(state: TrainState, batch: CloneBatch, key):
            loss, grads = _sharded_grads(state.params, batch, key)
            updates, opt_state = self.tx.update(
                grads, state.opt_state, state.params
            )
            params = optax.apply_updates(state.params, updates)
            return (
                TrainState(
                    params=params, opt_state=opt_state, step=state.step + 1
                ),
                loss,
            )

        @partial(
            shard_map,
            mesh=mesh,
            in_specs=(param_specs, batch_specs),
            out_specs=(P(("dp",)),) * 4,
            check_vma=False,
        )
        def _sharded_eval(params, batch):
            local = jax.tree.map(lambda x: x[0], batch)
            logits = gen.clone_forward(ccfg, params, local.pair_ids)
            per = optax.softmax_cross_entropy_with_integer_labels(
                logits, local.labels
            )
            probs = jax.nn.softmax(logits)[:, 1]
            return probs[None], local.labels[None], local.row_mask[None], per[None]

        @jax.jit
        def eval_step(params, batch: CloneBatch):
            return _sharded_eval(params, batch)

        self.train_step = train_step
        self.eval_step = eval_step

    def evaluate(self, state_or_params, batches: Iterable[CloneBatch]):
        params = getattr(state_or_params, "params", state_or_params)
        m = BinaryClassificationMetrics()
        loss_sum = count = 0.0
        for batch in batches:
            probs, labels, mask, per = jax.device_get(
                self.eval_step(params, batch)
            )
            m.update(probs, labels, mask)
            valid = np.asarray(mask, bool)
            loss_sum += float(np.asarray(per, np.float64)[valid].sum())
            count += float(valid.sum())
        metrics = m.compute()
        metrics["loss"] = loss_sum / count if count else float("nan")
        return metrics, m

    def fit(
        self,
        state: TrainState,
        train_batches: Callable[[int], Iterable[CloneBatch]],
        val_batches: Callable[[], Iterable[CloneBatch]] | None = None,
        checkpoints=None,
        max_epochs: int | None = None,
        patience: int | None = None,
        log_fn: Callable[[dict], None] | None = None,
        seed: int = 0,
    ) -> TrainState:
        tcfg = self.cfg.train
        max_epochs = max_epochs if max_epochs is not None else tcfg.max_epochs
        root = jax.random.key(seed)
        step = int(jax.device_get(state.step))
        best_f1, not_inc = -1.0, 0
        for epoch in range(max_epochs):
            t0 = time.perf_counter()
            losses = []
            for batch in train_batches(epoch):
                key = jax.random.fold_in(root, step)
                state, loss = self.train_step(state, batch, key)
                losses.append(loss)
                step += 1
            record = {
                "epoch": epoch,
                "train_loss": float(np.mean(jax.device_get(losses)))
                if losses
                else float("nan"),
                "epoch_seconds": time.perf_counter() - t0,
            }
            if val_batches is not None:
                metrics, _ = self.evaluate(state, val_batches())
                record.update({f"val_{k}": v for k, v in metrics.items()})
                f1 = metrics.get("f1", 0.0)
                if f1 > best_f1:
                    best_f1, not_inc = f1, 0
                else:
                    not_inc += 1
            if checkpoints is not None and (
                any(k.startswith("val_") for k in record)
                or (epoch + 1) % max(1, tcfg.checkpoint_every_epochs) == 0
                or epoch == max_epochs - 1
            ):
                checkpoints.save(
                    f"epoch-{epoch:04d}",
                    jax.device_get(state.params),
                    {
                        k: float(v)
                        for k, v in record.items()
                        if isinstance(v, (int, float)) and k != "epoch"
                    },
                    step=step,
                )
            logger.info("epoch %d: %s", epoch, record)
            if log_fn is not None:
                log_fn(record)
            if patience and not_inc > patience:
                logger.info("early stop: F1 stagnant for %d epochs", not_inc)
                break
        return state
