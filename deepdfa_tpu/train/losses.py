"""Losses and label extraction for graph batches.

Reference semantics (DDFA/code_gnn/models/base_module.py):
- label styles (get_label, base_module.py:83-95): "graph" = max over the
  batch-graph's node _VULN labels; "node" = per-node labels.
- loss = BCEWithLogitsLoss with optional pos_weight
  (base_module.py:74, datamodule.py:98-108 positive_weight).

All reductions are masked means over the valid (non-padding) slots so the
padded static shapes never bias the loss.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deepdfa_tpu.graphs.batch import GraphBatch
from deepdfa_tpu.nn.gnn import segment_max


def graph_labels(batch: GraphBatch) -> jax.Array:
    """Graph-level labels: max of node vuln per graph (padding-safe), OR'd
    with the stored graph_label so graph-only-labeled datasets (e.g. Devign:
    no per-statement annotations) are not silently negated."""
    vuln = jnp.where(batch.node_mask, batch.node_vuln, 0)
    per_graph = segment_max(
        vuln, batch.node_graph, batch.num_graphs + 1, indices_are_sorted=True
    )[: batch.num_graphs]
    derived = jnp.maximum(per_graph, 0).astype(jnp.float32)
    return jnp.maximum(derived, batch.graph_label)


def node_labels(batch: GraphBatch) -> jax.Array:
    return batch.node_vuln.astype(jnp.float32)


def dataflow_labels(batch: GraphBatch, style: str) -> tuple[jax.Array, jax.Array]:
    """(labels, mask), both [N, B]: the exact reaching-definitions IN/OUT
    fixpoint bits (reference base_module.py:83-95 dataflow_solution_*);
    the node mask broadcasts over the bit axis."""
    if style == "dataflow_solution_in":
        bits = batch.node_bits_in
    elif style == "dataflow_solution_out":
        bits = batch.node_bits_out
    else:
        raise ValueError(f"unsupported dataflow label_style: {style}")
    if bits is None:
        raise ValueError(
            f"label_style={style} requires bit labels on the batch "
            "(extract with max_defs set)"
        )
    mask = jnp.broadcast_to(batch.node_mask[:, None], bits.shape)
    return bits, mask


def bce_elements(
    logits: jax.Array,
    labels: jax.Array,
    pos_weight: float | jax.Array = 1.0,
) -> jax.Array:
    """Per-element binary cross-entropy on logits, torch-compatible.

    loss_i = -[pos_weight * y_i * log sigmoid(x_i) + (1-y_i) * log sigmoid(-x_i)]
    """
    log_p = jax.nn.log_sigmoid(logits)
    log_not_p = jax.nn.log_sigmoid(-logits)
    return -(pos_weight * labels * log_p + (1.0 - labels) * log_not_p)


def bce_with_logits(
    logits: jax.Array,
    labels: jax.Array,
    mask: jax.Array,
    pos_weight: float | jax.Array = 1.0,
) -> jax.Array:
    """Masked mean binary cross-entropy on logits."""
    per = bce_elements(logits, labels, pos_weight)
    mask = mask.astype(per.dtype)
    return jnp.sum(per * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def classifier_loss(
    logits: jax.Array,
    batch: GraphBatch,
    label_style: str = "graph",
    pos_weight: float | jax.Array = 1.0,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Returns (loss, labels, mask) for the configured label style."""
    if label_style == "graph":
        labels = graph_labels(batch)
        mask = batch.graph_mask
    elif label_style == "node":
        labels = node_labels(batch)
        mask = batch.node_mask
    elif label_style in ("dataflow_solution_in", "dataflow_solution_out"):
        labels, mask = dataflow_labels(batch, label_style)
    else:
        raise ValueError(f"unsupported label_style: {label_style}")
    return bce_with_logits(logits, labels, mask, pos_weight), labels, mask
