"""Class-imbalance samplers.

Reference semantics (DDFA/sastvd/helpers/dclass.py:84-105
`get_epoch_indices`): Big-Vul is ~6% vulnerable, so each training epoch
draws all positives plus an equal-size fresh random subset of negatives
(1:1 undersampling, resampled per epoch). Oversampling duplicates
positives up to the negative count instead.
"""

from __future__ import annotations

import numpy as np


def undersample_epoch(
    labels: np.ndarray, epoch: int, seed: int, ratio: float = 1.0
) -> np.ndarray:
    """Indices for one epoch: all positives + ratio*|pos| random negatives."""
    labels = np.asarray(labels)
    pos = np.flatnonzero(labels > 0)
    neg = np.flatnonzero(labels <= 0)
    rng = np.random.default_rng(np.random.SeedSequence([seed, epoch]))
    n_neg = min(len(neg), int(round(len(pos) * ratio))) if len(pos) else len(neg)
    chosen_neg = rng.choice(neg, size=n_neg, replace=False)
    idx = np.concatenate([pos, chosen_neg])
    rng.shuffle(idx)
    return idx


def oversample_epoch(labels: np.ndarray, epoch: int, seed: int) -> np.ndarray:
    """Indices with positives resampled (with replacement) to |neg|."""
    labels = np.asarray(labels)
    pos = np.flatnonzero(labels > 0)
    neg = np.flatnonzero(labels <= 0)
    rng = np.random.default_rng(np.random.SeedSequence([seed, epoch, 1]))
    if len(pos) == 0:
        idx = neg.copy()
    else:
        idx = np.concatenate([neg, rng.choice(pos, size=len(neg), replace=True)])
    rng.shuffle(idx)
    return idx


def positive_weight(labels: np.ndarray) -> float:
    """pos_weight = |neg| / |pos| (reference datamodule.py:98-108)."""
    labels = np.asarray(labels)
    npos = int((labels > 0).sum())
    nneg = int((labels <= 0).sum())
    return nneg / max(npos, 1)
