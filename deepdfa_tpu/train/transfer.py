"""Encoder transfer: load + freeze a pretrained DeepDFA graph encoder.

Reference workflow (--freeze_graph, DDFA/code_gnn/main_cli.py:136-145 and
the combined training recipe): train the GGNN alone first, then load its
weights minus the output/pooling layers into the combined model and freeze
them while the transformer fine-tunes.

JAX equivalents here:
- `graph_encoder_subset`: strip a trained DeepDFA param tree down to the
  encoder part (embeddings + GGNN; pooling/head dropped),
- `load_graph_encoder`: splice it into a combined model's "graph" subtree,
- `freeze_mask` + `frozen_optimizer`: optax.masked so frozen leaves get
  zero updates while everything else trains normally.
"""

from __future__ import annotations

from typing import Any

import jax
import optax


def graph_encoder_subset(deepdfa_params: Any, keep_pooling: bool = True) -> dict:
    """Keep embeddings + ggnn (+ optionally pooling — the combined model's
    encoder_mode uses attention pooling, so it transfers too); drop the
    classification head (reference drops output/pooling layers)."""
    p = deepdfa_params["params"] if "params" in deepdfa_params else deepdfa_params
    keep = {"embedding", "ggnn"} | ({"pooling"} if keep_pooling else set())
    sub = {k: v for k, v in p.items() if k in keep}
    missing = keep - set(sub)
    if missing:
        raise KeyError(f"graph encoder params missing {sorted(missing)}")
    return {"params": sub}


def load_graph_encoder(
    combined_params: dict, deepdfa_params: Any, keep_pooling: bool = True
) -> dict:
    """Return combined params with the graph subtree replaced by the
    pretrained encoder weights."""
    sub = graph_encoder_subset(deepdfa_params, keep_pooling)
    out = dict(combined_params)
    graph = dict(out["graph"]["params"] if "params" in out["graph"] else out["graph"])
    graph.update(sub["params"])
    out["graph"] = {"params": graph}
    return out


def freeze_mask(params: dict, frozen_top_keys: tuple[str, ...] = ("graph",)) -> Any:
    """Boolean pytree: True = trainable, False = frozen."""
    return {
        k: jax.tree.map(lambda _: k not in frozen_top_keys, v)
        for k, v in params.items()
    }


def frozen_optimizer(
    tx: optax.GradientTransformation,
    params: dict | None = None,
    frozen_top_keys: tuple[str, ...] = ("graph",),
) -> optax.GradientTransformation:
    """Wrap an optimizer so frozen subtrees receive zero updates.

    With params=None the masks are callables resolved at tx.init time, so
    the wrapper can be installed before parameters exist (trainer
    construction)."""
    if params is not None:
        mask = freeze_mask(params, frozen_top_keys)
        inv = jax.tree.map(lambda t: not t, mask)
    else:
        mask = lambda p: freeze_mask(p, frozen_top_keys)  # noqa: E731
        inv = lambda p: jax.tree.map(  # noqa: E731
            lambda t: not t, freeze_mask(p, frozen_top_keys)
        )
    return optax.chain(
        optax.masked(tx, mask),
        optax.masked(optax.set_to_zero(), inv),
    )
