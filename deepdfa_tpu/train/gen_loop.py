"""Trainer for the seq2seq generation tasks (the run_gen path).

Role parity with CodeT5/run_gen.py (and run_multi_gen.py's per-task loop):
AdamW + linear warmup, per-epoch dev perplexity, optional dev BLEU/EM via
beam-search decoding, checkpoint-best-ppl / checkpoint-best-bleu, and the
reference's dual-counter early stopping (run_gen.py:398-405: stop only
when BOTH the ppl counter and the bleu counter exceed patience).

TPU-first differences: the train step is a shard_map over the dp mesh
axis with exact global-token-count loss normalization (1-device ==
N-device); eval decoding is jit-compiled beam search (models/t5_gen.py)
instead of HF generate; BLEU comes from eval/codebleu.corpus_bleu
(smooth_bleu role) computed on decoded token sequences.
"""

from __future__ import annotations

import logging
import time
from functools import partial
from typing import Callable, Iterable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, PartitionSpec as P

from deepdfa_tpu.parallel.compat import shard_map

from deepdfa_tpu.core.config import Config
from deepdfa_tpu.data.gen_data import GenBatch
from deepdfa_tpu.models import t5_gen as gen
from deepdfa_tpu.parallel import sharding
from deepdfa_tpu.parallel.mesh import make_mesh
from deepdfa_tpu.train.state import TrainState, make_optimizer

logger = logging.getLogger(__name__)


class GenTrainer:
    """dp trainer for GenConfig seq2seq models."""

    def __init__(
        self,
        cfg: Config,
        gen_cfg: gen.GenConfig,
        mesh: Mesh | None = None,
        total_steps: int | None = None,
    ):
        self.cfg = cfg
        self.gen_cfg = gen_cfg
        self.mesh = mesh if mesh is not None else make_mesh(cfg.train.mesh)
        self.tx = make_optimizer(cfg.train.optim, total_steps)
        # the unified sharding layer (parallel/sharding.py): the gen
        # family's map resolves replicated on a dp mesh; rules from
        # MeshConfig.rules can reshard without touching this trainer
        self.sharding_map = sharding.sharding_map_for(
            "gen", mesh_shape=dict(self.mesh.shape),
            extra_rules=getattr(cfg.train.mesh, "rules", ()),
        )
        self._build_steps()

    def _place_params(self, params):
        return self.sharding_map.place(self.mesh, params)

    def make_checkpoints(self, directory, monitor="val_ppl", mode="min"):
        from deepdfa_tpu.train.checkpoint import CheckpointManager

        return CheckpointManager(
            directory, monitor=monitor, mode=mode,
            keep_last=getattr(self.cfg.train, "checkpoint_keep_last", 0),
        )

    def init_state(self, seed: int | None = None) -> TrainState:
        seed = self.cfg.train.seed if seed is None else seed
        params = gen.init_gen_params(self.gen_cfg, jax.random.key(seed))
        params = self._place_params(params)
        return TrainState.create(params, self.tx)

    def load_params(self, state: TrainState, params) -> TrainState:
        params = self._place_params(jax.device_get(params))
        return TrainState(
            params=params, opt_state=self.tx.init(params), step=state.step
        )

    # -- compiled steps ------------------------------------------------------

    def _build_steps(self) -> None:
        mesh = self.mesh
        gcfg = self.gen_cfg
        batch_specs = GenBatch(
            source_ids=P(("dp",)), target_ids=P(("dp",)), row_mask=P(("dp",))
        )
        param_specs = jax.tree.map(lambda _: P(), jax.eval_shape(
            lambda: gen.init_gen_params(gcfg, jax.random.key(0))
        ))

        def _local_token_loss(params, local: GenBatch, key):
            """(CE sum over valid tokens, token count) on this dp member."""
            pad = gcfg.encoder.pad_token_id
            logits = gen.seq2seq_logits(
                gcfg, params, local.source_ids, local.target_ids,
                dropout_key=key,
            )
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            tok_lp = jnp.take_along_axis(
                logp, local.target_ids[..., None], axis=-1
            )[..., 0]
            mask = (
                (local.target_ids != pad)
                & local.row_mask[:, None]
            ).astype(jnp.float32)
            return -(tok_lp * mask).sum(), mask.sum()

        @partial(
            shard_map,
            mesh=mesh,
            in_specs=(param_specs, batch_specs, P()),
            out_specs=(P(), param_specs),
            check_vma=False,
        )
        def _sharded_grads(params, batch, key):
            local = jax.tree.map(lambda x: x[0], batch)
            key = jax.random.fold_in(key, jax.lax.axis_index("dp"))
            count = _local_token_loss(params, local, None)[1]
            count_g = jnp.maximum(jax.lax.psum(count, "dp"), 1.0)

            def fn(p):
                return _local_token_loss(p, local, key)[0] / count_g

            loss_local, grads = jax.value_and_grad(fn)(params)
            loss = jax.lax.psum(loss_local, "dp")
            grads = jax.tree.map(lambda g: jax.lax.psum(g, "dp"), grads)
            return loss, grads

        @partial(jax.jit, donate_argnums=0)
        def train_step(state: TrainState, batch: GenBatch, key):
            loss, grads = _sharded_grads(state.params, batch, key)
            updates, opt_state = self.tx.update(
                grads, state.opt_state, state.params
            )
            params = optax.apply_updates(state.params, updates)
            return (
                TrainState(
                    params=params, opt_state=opt_state, step=state.step + 1
                ),
                loss,
            )

        @partial(jax.jit, donate_argnums=0)
        def train_step_guarded(state: TrainState, batch: GenBatch, key, lr_scale):
            """Divergence-guarded step: the shared on-device skip/select
            core lives in train/resilience.py:apply_guarded_update."""
            from deepdfa_tpu.train.resilience import apply_guarded_update

            loss, grads = _sharded_grads(state.params, batch, key)
            return apply_guarded_update(self.tx, state, loss, grads, lr_scale)

        @partial(
            shard_map,
            mesh=mesh,
            in_specs=(param_specs, batch_specs),
            out_specs=P(),
            check_vma=False,
        )
        def _sharded_eval(params, batch):
            local = jax.tree.map(lambda x: x[0], batch)
            s, c = _local_token_loss(params, local, None)
            return jnp.stack(
                [jax.lax.psum(s, "dp"), jax.lax.psum(c, "dp")]
            )

        @jax.jit
        def eval_step(params, batch: GenBatch):
            return _sharded_eval(params, batch)

        @partial(jax.jit, static_argnums=(2, 3))
        def decode_step(params, source_ids, beam_size, max_length):
            return gen.beam_search(
                self.gen_cfg, params, source_ids,
                beam_size=beam_size, max_length=max_length,
            )

        self.train_step = train_step
        self.train_step_guarded = train_step_guarded
        self.eval_step = eval_step
        self._decode_step = decode_step

    # -- evaluation ----------------------------------------------------------

    def eval_ppl(self, state_or_params, batches: Iterable[GenBatch]) -> float:
        """Token-weighted dev perplexity (run_gen.py:eval_ppl_epoch role)."""
        params = getattr(state_or_params, "params", state_or_params)
        s = c = 0.0
        for batch in batches:
            sc = np.asarray(jax.device_get(self.eval_step(params, batch)))
            s += float(sc[0])
            c += float(sc[1])
        return float(np.exp(s / max(c, 1.0)))

    def decode(
        self,
        state_or_params,
        source_ids: np.ndarray,
        beam_size: int | None = None,
        max_length: int | None = None,
        batch_rows: int = 16,
    ) -> list[list[int]]:
        """Beam-search decode unsharded sources -> trimmed token id lists."""
        params = getattr(state_or_params, "params", state_or_params)
        K = beam_size or self.gen_cfg.beam_size
        T = max_length or self.gen_cfg.max_target_length
        out: list[list[int]] = []
        n = source_ids.shape[0]
        for i in range(0, n, batch_rows):
            chunk = source_ids[i : i + batch_rows]
            pad_rows = batch_rows - chunk.shape[0]
            if pad_rows:
                chunk = np.concatenate(
                    [chunk, np.zeros((pad_rows, chunk.shape[1]), chunk.dtype)]
                )
            ids = np.asarray(
                jax.device_get(
                    self._decode_step(params, chunk.astype(np.int32), K, T)
                )
            )
            out.extend(
                gen.trim_at_eos(
                    ids[: batch_rows - pad_rows],
                    eos_id=self.gen_cfg.encoder.eos_token_id,
                    pad_id=self.gen_cfg.encoder.pad_token_id,
                )
            )
        return out

    def eval_bleu_em(
        self,
        state_or_params,
        source_ids: np.ndarray,
        target_token_lists: Sequence[Sequence[int]],
        beam_size: int | None = None,
        return_preds: bool = False,
    ) -> dict:
        """Dev BLEU + exact match on token sequences
        (run_gen.py:eval_bleu_epoch role; BLEU from eval/codebleu)."""
        from deepdfa_tpu.eval.codebleu import corpus_bleu

        preds = self.decode(state_or_params, source_ids, beam_size=beam_size)
        refs = [list(map(int, t)) for t in target_token_lists]
        em = float(
            np.mean([p == r for p, r in zip(preds, refs)])
        ) * 100.0
        bleu = corpus_bleu(
            [[list(map(str, r))] for r in refs],
            [list(map(str, p)) for p in preds],
        ) * 100.0
        out = {"bleu": bleu, "em": em, "bleu_em": bleu + em}
        if return_preds:
            out["preds"] = preds
        return out

    # -- fit -----------------------------------------------------------------

    def fit(
        self,
        state: TrainState,
        train_batches: Callable[[int], Iterable[GenBatch]],
        val_batches: Callable[[], Iterable[GenBatch]] | None = None,
        val_decode: tuple[np.ndarray, Sequence[Sequence[int]]] | None = None,
        checkpoints=None,
        bleu_checkpoints=None,
        max_epochs: int | None = None,
        patience: int | None = None,
        log_fn: Callable[[dict], None] | None = None,
        seed: int = 0,
        resilience=None,
    ) -> TrainState:
        """val_decode: (source_ids, target token lists) for dev BLEU/EM.

        Early stopping mirrors run_gen.py:398-405: stop when the ppl
        no-decrease counter AND the bleu no-increase counter both exceed
        `patience` (bleu counter starts "infinite" when BLEU eval is off).

        resilience: an optional train/resilience.py ResilientRunner —
        step-granular checkpoint/resume, divergence guard, preemption
        handling, watchdog. A mid-epoch resume restores the exact
        TrainState and fast-forwards the (deterministically shuffled)
        batch stream; the best-ppl/bleu early-stop counters restart at
        the resumed epoch (they are derived, not part of the state).
        """
        import contextlib

        from deepdfa_tpu import obs
        from deepdfa_tpu.train.resilience import (
            ResumeCursor,
            finite_mean,
            place_like,
            skip_first,
        )

        # unified telemetry (docs/observability.md): no-op unless enabled
        inst = obs.instruments(self.cfg)
        tcfg = self.cfg.train
        max_epochs = max_epochs if max_epochs is not None else tcfg.max_epochs
        patience = patience if patience is not None else getattr(
            tcfg, "early_stop_patience", 0
        )
        root = jax.random.key(seed)
        res = resilience
        guard = res is not None and res.guard_active
        start_epoch = skip_batches = 0
        cursor = None
        if res is not None:
            state, cursor = res.maybe_resume(state, place_like(state))
            if cursor is not None:
                start_epoch, skip_batches = cursor.epoch, cursor.batch_index
        # on resume the loop step comes from the DATA cursor, not
        # state.step: guard-skipped steps leave state.step behind the
        # host count the cursor (and RNG folding) was aligned to
        step = (
            cursor.step if cursor is not None
            else int(jax.device_get(state.step))
        )
        best_ppl, best_bleu_em = float("inf"), -1.0
        not_ppl_dec = 0
        not_bleu_inc = 0 if val_decode is not None else float("inf")
        cm = res if res is not None else contextlib.nullcontext()
        with cm:
            for epoch in range(start_epoch, max_epochs):
                t0 = time.perf_counter()
                losses = []
                source = train_batches(epoch)
                batch_index = 0
                if epoch == start_epoch and skip_batches:
                    # deterministic fast-forward (shuffle is seeded by
                    # epoch) on the raw source, with a beat per skipped
                    # pull — a cold fast-forward can outlast the
                    # watchdog's first-step grace
                    source = skip_first(
                        source, skip_batches,
                        heartbeat=lambda: res.heartbeat(
                            "input", epoch=epoch, step=step
                        ),
                    )
                    batch_index = skip_batches
                it = iter(source)
                while True:
                    if res is not None:
                        res.heartbeat("input", epoch=epoch, step=step)
                    try:
                        batch = next(it)
                    except StopIteration:
                        break
                    if res is not None:
                        res.heartbeat("device", epoch=epoch, step=step)
                    key = jax.random.fold_in(root, step)
                    with inst.step_span(step):
                        if guard:
                            state, loss, ok = self.train_step_guarded(
                                state, batch, key, res.lr_scale()
                            )
                        else:
                            state, loss = self.train_step(state, batch, key)
                            ok = None
                    inst.dispatched(loss)
                    losses.append(loss)
                    step += 1
                    batch_index += 1
                    if res is not None:
                        state = res.after_step(
                            state, ok, ResumeCursor(epoch, batch_index, step)
                        )
                record = {
                    "epoch": epoch,
                    # guarded runs exclude skipped steps' poisoned losses
                    # from the epoch aggregate (see GraphTrainer.fit)
                    "train_loss": (
                        (finite_mean(jax.device_get(losses)) if guard
                         else float(np.mean(jax.device_get(losses))))
                        if losses else float("nan")
                    ),
                    "epoch_seconds": time.perf_counter() - t0,
                }
                if res is not None:
                    record.update(res.record())
                    # epoch-end stages (ppl eval, BLEU decode, orbax
                    # saves) run under the watchdog's grace threshold
                    res.heartbeat("eval", epoch=epoch)
                # attach the obs registry snapshot + device memory
                # (identical record when telemetry is off)
                inst.finish_epoch(record)
                if val_batches is not None:
                    ppl = self.eval_ppl(state, val_batches())
                    record["val_ppl"] = ppl
                    if ppl < best_ppl:
                        best_ppl, not_ppl_dec = ppl, 0
                        if checkpoints is not None:
                            checkpoints.save(
                                f"epoch-{epoch:04d}",
                                jax.device_get(state.params),
                                {"val_ppl": ppl},
                                step=step,
                            )
                    else:
                        not_ppl_dec += 1
                elif checkpoints is not None and (
                    (epoch + 1) % max(1, tcfg.checkpoint_every_epochs) == 0
                    or epoch == max_epochs - 1
                ):
                    checkpoints.save(
                        f"epoch-{epoch:04d}", jax.device_get(state.params), {},
                        step=step,
                    )
                if val_decode is not None:
                    src, refs = val_decode
                    bleu = self.eval_bleu_em(state, src, refs)
                    record.update({f"val_{k}": v for k, v in bleu.items()})
                    if bleu["bleu_em"] > best_bleu_em:
                        best_bleu_em, not_bleu_inc = bleu["bleu_em"], 0
                        if bleu_checkpoints is not None:
                            bleu_checkpoints.save(
                                f"epoch-{epoch:04d}",
                                jax.device_get(state.params),
                                {"val_bleu_em": bleu["bleu_em"]},
                                step=step,
                            )
                    else:
                        not_bleu_inc += 1
                logger.info("epoch %d: %s", epoch, record)
                if log_fn is not None:
                    log_fn(record)
                if patience and not_ppl_dec > patience and not_bleu_inc > patience:
                    logger.info(
                        "early stop: ppl counter %d, bleu counter %s > patience %d",
                        not_ppl_dec, not_bleu_inc, patience,
                    )
                    break
            if res is not None:
                state = res.finish(state, ResumeCursor(max_epochs, 0, step))
        return state
