"""Compiled training/eval steps + the epoch loop for graph classifiers.

Replaces the reference's Lightning trainer stack
(DDFA/code_gnn/main_cli.py fit/test, base_module.py train/val/test steps):

- one jit-compiled `train_step` (train state donated) per static batch
  signature; the bucketed batcher guarantees a single signature per run.
- data parallelism rides the unified sharding layer
  (parallel/sharding.py, docs/sharding.md): every batch carries a fixed
  number of LOGICAL shards on its leading axis (from `pack_shards`),
  shard_map over the `dp` mesh axis hands each device its block, and
  per-shard masked loss *sums* / gradients-of-sum are computed under
  `jax.vmap` — so a shard's compute never depends on how many devices
  share the batch. Reductions are `gather_logical` (ordered all_gather
  to the fixed [num_shards, ...] layout) + one fixed-shape sum instead
  of a per-topology psum tree: the global mean stays exact under
  unequal shard graph counts AND the step-loss trajectory is
  BIT-IDENTICAL across dp topologies that divide num_shards — the
  elastic-resume contract (tests/test_sharding.py). With a 1-device
  mesh the same code path compiles to no collectives, so single-chip
  and pod share one implementation.
- metrics stream into host-side accumulators; eval loss is computed on
  device from logits (identical semantics to the training objective) and
  accumulated as an exact masked mean across batches.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from functools import partial
from typing import Callable, Iterable

import jax
import numpy as np
import optax
from jax.sharding import Mesh, PartitionSpec as P

from deepdfa_tpu.core.config import Config
from deepdfa_tpu.graphs.batch import NUM_SUBKEY_FEATS, GraphBatch
from deepdfa_tpu.parallel import sharding
from deepdfa_tpu.parallel.compat import shard_map
from deepdfa_tpu.parallel.mesh import make_mesh
from deepdfa_tpu.train.checkpoint import CheckpointManager
from deepdfa_tpu.train.losses import (
    bce_elements,
    classifier_loss,
    dataflow_labels,
    graph_labels,
    node_labels,
)
from deepdfa_tpu.train.metrics import BinaryClassificationMetrics
from deepdfa_tpu.train.state import TrainState, make_optimizer

logger = logging.getLogger(__name__)


def drop_known_feats(node_feats, key, rate: float):
    """Feature-identity dropout: with probability `rate` per NODE, map
    every known vocab bucket (index >= 2) down to UNKNOWN (1), keeping
    the 0 (not-a-def-in-this-view) pattern intact.

    Motivation (round 4): vocabularies are built from the train split
    only, so an unseen bug family's definitions arrive as UNKNOWN at
    test time — a model that keys on specific buckets transfers nothing
    to them (the cross-template analog of the reference's cross-project
    drop, paper Table 7). Training with some defs randomly anonymized
    forces structure-based decisions (which defs REACH the use) to carry
    weight alongside bucket identity. One jnp.where — free on TPU."""
    import jax.numpy as jnp

    drop = jax.random.bernoulli(key, rate, (node_feats.shape[0],))
    if node_feats.ndim == 1:
        return jnp.where(drop, jnp.minimum(node_feats, 1), node_feats)
    dropped = jnp.where(
        drop[:, None], jnp.minimum(node_feats, 1), node_feats
    )
    if node_feats.shape[1] > NUM_SUBKEY_FEATS:
        # structural columns (frontend/structfeat.py) have no UNKNOWN
        # semantics — they are family-invariant by construction and must
        # never be anonymized (a struct value clamped to 1 would be a
        # DIFFERENT valid bucket, not "unknown")
        dropped = jnp.concatenate(
            [dropped[:, :NUM_SUBKEY_FEATS],
             node_feats[:, NUM_SUBKEY_FEATS:]], axis=1
        )
    return dropped


class GraphTrainer:
    """Train/eval driver for models taking a GraphBatch and emitting logits."""

    def __init__(
        self,
        model,
        cfg: Config,
        mesh: Mesh | None = None,
        pos_weight: float | None = None,
        total_steps: int | None = None,
    ):
        self.model = model
        self.cfg = cfg
        self.mesh = mesh if mesh is not None else make_mesh(cfg.train.mesh)
        if pos_weight is None:
            pos_weight = cfg.train.pos_weight if cfg.train.pos_weight is not None else 1.0
        self.pos_weight = float(pos_weight)
        self.tx = make_optimizer(cfg.train.optim, total_steps)
        self.label_style = getattr(model, "label_style", "graph")
        if self.label_style not in (
            "graph", "node", "dataflow_solution_in", "dataflow_solution_out"
        ):
            raise ValueError(f"unsupported label_style: {self.label_style}")
        self.feat_dropout = float(
            getattr(cfg.train, "feat_unknown_dropout", 0.0)
        )
        self._build_steps()

    # -- construction -------------------------------------------------------

    def init_state(self, example_batch: GraphBatch, seed: int | None = None) -> TrainState:
        seed = self.cfg.train.seed if seed is None else seed
        local = sharding.split_logical(example_batch, 0)
        params = self.model.init(jax.random.key(seed), local)
        state = TrainState.create(params, self.tx)
        return sharding.place_params(self.mesh, state)

    def make_checkpoints(self, directory) -> CheckpointManager:
        """CheckpointManager wired to the configured monitor metric."""
        return CheckpointManager(
            directory,
            monitor=self.cfg.train.monitor,
            mode=self.cfg.train.monitor_mode,
            keep_last=getattr(self.cfg.train, "checkpoint_keep_last", 0),
        )

    def _labels_mask(self, batch: GraphBatch):
        if self.label_style == "graph":
            return graph_labels(batch), batch.graph_mask
        if self.label_style.startswith("dataflow_solution"):
            return dataflow_labels(batch, self.label_style)
        return node_labels(batch), batch.node_mask

    def _local_loss_sum(self, params, batch: GraphBatch):
        """Masked SUM of per-example losses + valid count (exact-mean dp)."""
        logits = self.model.apply(params, batch)
        labels, mask = self._labels_mask(batch)
        per = bce_elements(logits, labels, self.pos_weight)
        m = mask.astype(per.dtype)
        return (per * m).sum(), m.sum()

    def _build_steps(self) -> None:
        mesh = self.mesh

        def _shard_loss_grads(params, local: GraphBatch, step):
            """(loss sum, count, grads) for ONE logical shard — vmapped
            over the device's shard block, so the per-shard program is
            identical on every dp topology (docs/sharding.md)."""
            if self.feat_dropout > 0:
                # deterministic per step (no RNG in TrainState, so
                # checkpoints stay compatible); every logical shard
                # applies the same positional mask to its local arrays —
                # augmentation, not a numerics contract
                key = jax.random.fold_in(
                    jax.random.key(self.cfg.train.seed + 7919), step
                )
                local = dataclasses.replace(
                    local,
                    node_feats=drop_known_feats(
                        local.node_feats, key, self.feat_dropout
                    ),
                )

            def loss_sum_fn(p):
                s, c = self._local_loss_sum(p, local)
                return s, c

            (loss_sum, count), grads = jax.value_and_grad(
                loss_sum_fn, has_aux=True
            )(params)
            return loss_sum, count, grads

        @partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(), P(("dp",)), P()),
            out_specs=(P(), P()),
            check_vma=False,
        )
        def _sharded_grads(params, batch, step):
            # batch leaves arrive as this device's [num_shards/dp, ...]
            # block of logical shards; per-shard sums/grads gather to the
            # FIXED [num_shards, ...] layout and reduce in one
            # fixed-shape sum — one reduction tree on every topology
            # (bit-identity across dp; parallel/sharding.py). tp/sp mesh
            # members compute replicated-true, so no reduction there.
            sums, counts, grads = jax.vmap(
                lambda shard: _shard_loss_grads(params, shard, step)
            )(batch)
            counts = sharding.gather_logical(counts)
            denom = jax.numpy.maximum(counts.sum(), 1.0)
            loss = sharding.gather_logical(sums).sum() / denom
            grads = jax.tree.map(
                lambda g: sharding.gather_logical(g).sum(axis=0) / denom,
                grads,
            )
            return loss, grads

        @partial(jax.jit, donate_argnums=0)
        def train_step(state: TrainState, batch: GraphBatch):
            loss, grads = _sharded_grads(state.params, batch, state.step)
            updates, opt_state = self.tx.update(grads, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            return (
                TrainState(params=params, opt_state=opt_state, step=state.step + 1),
                loss,
            )

        @partial(jax.jit, donate_argnums=0)
        def train_step_guarded(state: TrainState, batch: GraphBatch, lr_scale):
            """Divergence-guarded step: the shared on-device skip/select
            core lives in train/resilience.py:apply_guarded_update."""
            from deepdfa_tpu.train.resilience import apply_guarded_update

            loss, grads = _sharded_grads(state.params, batch, state.step)
            return apply_guarded_update(self.tx, state, loss, grads, lr_scale)

        @partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(), P(("dp",))),
            out_specs=(P("dp"), P("dp"), P("dp"), P("dp")),
            check_vma=False,
        )
        def _sharded_eval(params, batch):
            def one(local):
                logits = self.model.apply(params, local)
                labels, mask = self._labels_mask(local)
                per = bce_elements(logits, labels, self.pos_weight)
                return jax.nn.sigmoid(logits), labels, mask, per

            # [num_shards/dp, ...] per leaf locally; the dp out_specs
            # reassemble the full [num_shards, ...] logical layout
            return jax.vmap(one)(batch)

        @jax.jit
        def eval_step(params, batch: GraphBatch):
            return _sharded_eval(params, batch)

        self.train_step = train_step
        self.train_step_guarded = train_step_guarded
        self.eval_step = eval_step

    # -- loops ---------------------------------------------------------------

    def evaluate(
        self, state_or_params, batches: Iterable[GraphBatch]
    ) -> tuple[dict[str, float], BinaryClassificationMetrics]:
        params = getattr(state_or_params, "params", state_or_params)
        m = BinaryClassificationMetrics()
        loss_sum = 0.0
        count = 0.0
        for batch in batches:
            probs, labels, mask, per = jax.device_get(
                self.eval_step(params, batch)
            )
            m.update(probs, labels, mask)
            valid = np.asarray(mask, bool)
            loss_sum += float(np.asarray(per, np.float64)[valid].sum())
            count += float(valid.sum())
        metrics = m.compute()
        metrics["loss"] = loss_sum / count if count else float("nan")
        return metrics, m

    def fit(
        self,
        state: TrainState,
        train_batches: Callable[[int], Iterable[GraphBatch]],
        val_batches: Callable[[], Iterable[GraphBatch]] | None = None,
        checkpoints: CheckpointManager | None = None,
        max_epochs: int | None = None,
        log_fn: Callable[[dict], None] | None = None,
        source_stage: str = "pack",
        resilience=None,
    ) -> TrainState:
        import contextlib

        from deepdfa_tpu import obs
        from deepdfa_tpu.data.prefetch import (
            PipelineStats,
            device_placer,
            prefetch,
        )
        from deepdfa_tpu.train.resilience import (
            ResumeCursor,
            finite_mean,
            place_like,
            skip_first,
        )

        # unified telemetry (docs/observability.md): step spans, lagged
        # step timing, epoch-record enrichment — a shared no-op unless
        # cfg.obs enables something (or tracing is already on)
        inst = obs.instruments(self.cfg)
        tcfg = self.cfg.train
        max_epochs = max_epochs if max_epochs is not None else tcfg.max_epochs
        res = resilience
        guard = res is not None and res.guard_active
        start_epoch = skip_batches = 0
        cursor = None
        if res is not None:
            # topology stamp for the resume manifest: elastic resume may
            # change dp (bit-identical when num_shards is unchanged);
            # maybe_resume warns loudly on a num_shards drift
            res.set_topology(sharding.mesh_record(
                self.mesh,
                sharding.logical_shards(self.cfg.train.mesh, self.mesh),
            ))
            state, cursor = res.maybe_resume(state, place_like(state))
            if cursor is not None:
                start_epoch, skip_batches = cursor.epoch, cursor.batch_index
        # on resume the loop step comes from the DATA cursor, not
        # state.step: guard-skipped steps and rollbacks leave state.step
        # behind the host count, and the cursor is what batch_index, RNG
        # folding, and checkpoint tags were aligned to pre-kill
        step = (
            cursor.step if cursor is not None
            else int(jax.device_get(state.step))
        )
        placer = device_placer(self.mesh)
        # efficiency ledger (docs/efficiency.md): once per distinct
        # batch signature, declare the StepTimer join site and AOT-read
        # the compiled step's cost analysis; the local memo keeps the
        # per-step cost at one string build + compare
        ledger_sig: str | None = None
        cm = res if res is not None else contextlib.nullcontext()
        with cm:
            for epoch in range(start_epoch, max_epochs):
                t0 = time.perf_counter()
                losses = []
                stats = PipelineStats()
                if res is not None:
                    res.attach_stats(stats)
                source = train_batches(epoch)
                # a source may know better than the static default which
                # stage its pulls are (cli _BatchStream: "load" on a warm
                # cache epoch, "pack" on a cold one)
                stage = getattr(source, "source_stage", source_stage)
                batch_index = 0
                if epoch == start_epoch and skip_batches:
                    # deterministic fast-forward: the stream is a pure
                    # function of (epoch, seed, digest), so dropping the
                    # batches the resumed checkpoint already consumed —
                    # BEFORE the prefetch pipeline, so they are never
                    # device_put or stats-counted — re-aligns data with
                    # the restored state
                    source = skip_first(
                        source, skip_batches,
                        heartbeat=lambda: res.heartbeat(
                            "input", epoch=epoch, step=step
                        ),
                    )
                    batch_index = skip_batches
                stream = prefetch(
                    source, tcfg.prefetch_batches, placer,
                    producers=tcfg.prefetch_producers,
                    stats=stats, source_stage=stage,
                )
                try:
                    it = iter(stream)
                    while True:
                        if res is not None:
                            res.heartbeat("input", epoch=epoch, step=step)
                        try:
                            batch = next(it)
                        except StopIteration:
                            break
                        if res is not None:
                            res.heartbeat("device", epoch=epoch, step=step)
                        if inst.ledger is not None:
                            sig = (
                                f"G{batch.num_graphs}"
                                f"xN{batch.node_feats.shape[-2]}"
                                f"xE{batch.edge_src.shape[-1]}"
                            )
                            if sig != ledger_sig:
                                ledger_sig = sig
                                inst.observe_step_compile(
                                    "train_step", sig,
                                    self.train_step_guarded if guard
                                    else self.train_step,
                                    (state, batch, res.lr_scale())
                                    if guard else (state, batch),
                                )
                        with inst.step_span(step):
                            if guard:
                                state, loss, ok = self.train_step_guarded(
                                    state, batch, res.lr_scale()
                                )
                            else:
                                state, loss = self.train_step(state, batch)
                                ok = None
                        inst.dispatched(loss)
                        losses.append(loss)
                        step += 1
                        batch_index += 1
                        if log_fn is not None and step % max(1, tcfg.log_every_steps) == 0:
                            log_fn({"step": step, "loss": float(jax.device_get(loss))})
                        # after the step's own logging: a preemption here
                        # raises, and the step it finished stays logged
                        if res is not None:
                            state = res.after_step(
                                state, ok,
                                ResumeCursor(epoch, batch_index, step),
                            )
                finally:
                    stream.close()  # joins prefetch producers on any exit
                # guarded runs: skipped steps carry the poisoned loss —
                # exclude non-finite values so a survived epoch does not
                # aggregate to NaN (skips stay visible via skipped_steps)
                train_loss = (
                    (finite_mean(jax.device_get(losses)) if guard
                     else float(np.mean(jax.device_get(losses))))
                    if losses else float("nan")
                )
                epoch_seconds = time.perf_counter() - t0
                record = {
                    "epoch": epoch,
                    "train_loss": train_loss,
                    "epoch_seconds": epoch_seconds,
                    # host-side stage attribution (docs/input_pipeline.md):
                    # pack/load = source assembly, place = H2D, wait = the
                    # fraction of the epoch the device sat input-starved
                    "host_load_seconds": round(stats.load_seconds, 3),
                    "host_pack_seconds": round(stats.pack_seconds, 3),
                    "host_place_seconds": round(stats.place_seconds, 3),
                    "input_wait_seconds": round(stats.wait_seconds, 3),
                    "input_wait_fraction": round(
                        stats.wait_fraction(epoch_seconds), 4
                    ),
                }
                if getattr(self.model, "ggnn_kernel", False):
                    # fused-kernel compile/step census (the PR-2
                    # step-cache convention): per-signature lowering
                    # counts + device propagation steps this epoch;
                    # flattens to ggnn_kernel/* tags (SCHEMA-declared).
                    # A census that grows after epoch 1 is a steady-
                    # state recompile — the same signal jit_lowerings()
                    # guards on the serve executors.
                    from deepdfa_tpu.nn import ggnn_kernel as ggnn_k

                    record["ggnn_kernel"] = ggnn_k.epoch_record(
                        steps=len(losses)
                        * getattr(self.model, "n_steps", 0)
                    )
                if res is not None:
                    # self-healing observables (docs/resilience.md):
                    # resumed_from_step / skipped_steps / rollbacks
                    record.update(res.record())
                # absorb the epoch's pipeline counters into the metrics
                # registry and attach the obs snapshot + device memory
                # (no-ops / identical record when telemetry is off)
                inst.observe_pipeline(stats)
                inst.finish_epoch(record)
                if val_batches is not None and (
                    (epoch + 1) % tcfg.eval_every_epochs == 0
                    or epoch == max_epochs - 1
                ):
                    if res is not None:
                        # epoch-end stages run under the watchdog's grace
                        # threshold, not the per-step timeout
                        res.heartbeat("eval", epoch=epoch)
                    val_metrics, _ = self.evaluate(state, val_batches())
                    record.update({f"val_{k}": v for k, v in val_metrics.items()})
                if checkpoints is not None and (
                    any(k.startswith("val_") for k in record)
                    or (epoch + 1) % max(1, tcfg.checkpoint_every_epochs) == 0
                    or epoch == max_epochs - 1
                ):
                    if res is not None:
                        res.heartbeat("checkpoint", epoch=epoch)
                    checkpoints.save(
                        f"epoch-{epoch:04d}",
                        jax.device_get(state.params),
                        {
                            k: float(v)
                            for k, v in record.items()
                            if k != "epoch" and isinstance(v, (int, float))
                        },
                        step=step,
                    )
                logger.info("epoch %d: %s", epoch, record)
                if log_fn is not None:
                    log_fn(record)
            if res is not None:
                # drain lagged guard flags + leave a final resume point
                # (a completed run re-invoked with auto-resume is a no-op)
                state = res.finish(state, ResumeCursor(max_epochs, 0, step))
        return state
