"""Compiled training/eval steps + the epoch loop for graph classifiers.

Replaces the reference's Lightning trainer stack
(DDFA/code_gnn/main_cli.py fit/test, base_module.py train/val/test steps):

- one jit-compiled `train_step` (params, opt_state donated) per static batch
  signature; the bucketed batcher guarantees a single signature per run.
- data parallelism is shard_map over the `dp` mesh axis: each device gets a
  whole-graph shard (leading axis from `pack_shards`), computes local loss
  and grads, and `psum`s them — the XLA-native equivalent of DDP gradient
  all-reduce. With a 1-device mesh the same code path compiles to no
  collectives, so single-chip and multi-chip share one implementation.
- metrics stream into host-side accumulators; best checkpoint is selected
  on the monitored metric like the reference's val_loss checkpointing.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from functools import partial
from typing import Callable, Iterable

import jax
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.6 stable API
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from deepdfa_tpu.core.config import Config
from deepdfa_tpu.graphs.batch import GraphBatch
from deepdfa_tpu.parallel.mesh import make_mesh
from deepdfa_tpu.train.checkpoint import CheckpointManager
from deepdfa_tpu.train.losses import classifier_loss
from deepdfa_tpu.train.metrics import BinaryClassificationMetrics
from deepdfa_tpu.train.state import TrainState, make_optimizer

logger = logging.getLogger(__name__)


def _squeeze_batch(batch: GraphBatch) -> GraphBatch:
    """Drop the unit leading (shard) axis inside shard_map."""
    arrays = {
        f.name: getattr(batch, f.name)[0]
        for f in dataclasses.fields(batch)
        if f.name != "num_graphs"
    }
    return GraphBatch(**arrays, num_graphs=batch.num_graphs)


class GraphTrainer:
    """Train/eval driver for models taking a GraphBatch and emitting logits."""

    def __init__(
        self,
        model,
        cfg: Config,
        mesh: Mesh | None = None,
        pos_weight: float = 1.0,
        total_steps: int | None = None,
    ):
        self.model = model
        self.cfg = cfg
        self.mesh = mesh if mesh is not None else make_mesh(cfg.train.mesh)
        self.pos_weight = float(pos_weight)
        self.tx = make_optimizer(cfg.train.optim, total_steps)
        self.label_style = getattr(model, "label_style", "graph")
        self._build_steps()

    # -- construction -------------------------------------------------------

    def init_state(self, example_batch: GraphBatch, seed: int | None = None) -> TrainState:
        seed = self.cfg.train.seed if seed is None else seed
        local = _squeeze_batch(example_batch)
        params = self.model.init(jax.random.key(seed), local)
        state = TrainState.create(params, self.tx)
        return jax.device_put(state, NamedSharding(self.mesh, P()))

    def _local_loss(self, params, batch: GraphBatch):
        logits = self.model.apply(params, batch)
        loss, labels, mask = classifier_loss(
            logits, batch, self.label_style, self.pos_weight
        )
        return loss, (logits, labels, mask)

    def _build_steps(self) -> None:
        mesh = self.mesh

        @partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(), P(("dp",))),
            out_specs=(P(), P()),
            check_vma=False,
        )
        def _sharded_grads(params, batch):
            local = _squeeze_batch(batch)
            (loss, _), grads = jax.value_and_grad(self._local_loss, has_aux=True)(
                params, local
            )
            grads = jax.lax.pmean(grads, "dp")
            grads = jax.lax.pmean(grads, "tp")
            grads = jax.lax.pmean(grads, "sp")
            loss = jax.lax.pmean(loss, ("dp", "tp", "sp"))
            return loss, grads

        @jax.jit
        def train_step(state: TrainState, batch: GraphBatch):
            loss, grads = _sharded_grads(state.params, batch)
            updates, opt_state = self.tx.update(grads, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            return (
                TrainState(params=params, opt_state=opt_state, step=state.step + 1),
                loss,
            )

        @partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(), P(("dp",))),
            out_specs=(P("dp"), P("dp"), P("dp")),
            check_vma=False,
        )
        def _sharded_eval(params, batch):
            local = _squeeze_batch(batch)
            _, (logits, labels, mask) = self._local_loss(params, local)
            probs = jax.nn.sigmoid(logits)
            return probs[None], labels[None], mask[None]

        @jax.jit
        def eval_step(params, batch: GraphBatch):
            return _sharded_eval(params, batch)

        self.train_step = train_step
        self.eval_step = eval_step

    # -- loops ---------------------------------------------------------------

    def evaluate(
        self, state_or_params, batches: Iterable[GraphBatch]
    ) -> tuple[dict[str, float], BinaryClassificationMetrics]:
        params = getattr(state_or_params, "params", state_or_params)
        m = BinaryClassificationMetrics()
        losses = []
        for batch in batches:
            probs, labels, mask = self.eval_step(params, batch)
            probs, labels, mask = jax.device_get((probs, labels, mask))
            m.update(probs, labels, mask)
            valid = np.asarray(mask, bool)
            p = np.clip(np.asarray(probs, np.float64), 1e-7, 1 - 1e-7)
            y = np.asarray(labels, np.float64)
            per = -(
                self.pos_weight * y * np.log(p) + (1 - y) * np.log1p(-p)
            )
            if valid.any():
                losses.append(per[valid].mean())
        metrics = m.compute()
        metrics["loss"] = float(np.mean(losses)) if losses else float("nan")
        return metrics, m

    def fit(
        self,
        state: TrainState,
        train_batches: Callable[[int], Iterable[GraphBatch]],
        val_batches: Callable[[], Iterable[GraphBatch]] | None = None,
        checkpoints: CheckpointManager | None = None,
        max_epochs: int | None = None,
        log_fn: Callable[[dict], None] | None = None,
    ) -> TrainState:
        max_epochs = max_epochs or self.cfg.train.max_epochs
        for epoch in range(max_epochs):
            t0 = time.perf_counter()
            losses = []
            for batch in train_batches(epoch):
                state, loss = self.train_step(state, batch)
                losses.append(loss)
            train_loss = float(np.mean(jax.device_get(losses))) if losses else float("nan")
            record = {
                "epoch": epoch,
                "train_loss": train_loss,
                "epoch_seconds": time.perf_counter() - t0,
            }
            if val_batches is not None and (
                (epoch + 1) % self.cfg.train.eval_every_epochs == 0
                or epoch == max_epochs - 1
            ):
                val_metrics, _ = self.evaluate(state, val_batches())
                record.update({f"val_{k}": v for k, v in val_metrics.items()})
                if checkpoints is not None:
                    checkpoints.save(
                        f"epoch-{epoch:04d}",
                        jax.device_get(state.params),
                        {k: float(v) for k, v in record.items() if k != "epoch"},
                        step=int(jax.device_get(state.step)),
                    )
            logger.info("epoch %d: %s", epoch, record)
            if log_fn is not None:
                log_fn(record)
        return state
