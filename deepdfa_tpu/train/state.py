"""Train state + optimizer construction.

Reference optimizer: Adam lr 1e-3, weight_decay 1e-2
(DDFA/configs/config_default.yaml:43-47 — torch Adam's weight_decay is L2
into the gradient; optax.adamw's decoupled decay is the idiomatic JAX
equivalent and trains at least as well). The transformer paths use AdamW
with linear warmup + clip (LineVul/linevul/linevul_main.py:150-162), which
maps to the same factory with warmup_frac/grad_clip_norm set.
"""

from __future__ import annotations

from typing import Any

import jax
import optax
from flax import struct

from deepdfa_tpu.core.config import OptimConfig


@struct.dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jax.Array

    @classmethod
    def create(cls, params, tx: optax.GradientTransformation) -> "TrainState":
        import jax.numpy as jnp

        return cls(params=params, opt_state=tx.init(params), step=jnp.zeros((), jnp.int32))


def make_optimizer(cfg: OptimConfig, total_steps: int | None = None) -> optax.GradientTransformation:
    if cfg.warmup_frac > 0.0:
        if not total_steps:
            raise ValueError("warmup_frac requires total_steps")
        warmup = max(1, int(total_steps * cfg.warmup_frac))
        schedule = optax.join_schedules(
            [
                optax.linear_schedule(0.0, cfg.learning_rate, warmup),
                optax.linear_schedule(
                    cfg.learning_rate, 0.0, max(1, total_steps - warmup)
                ),
            ],
            boundaries=[warmup],
        )
    else:
        schedule = cfg.learning_rate

    parts = []
    if cfg.grad_clip_norm > 0.0:
        parts.append(optax.clip_by_global_norm(cfg.grad_clip_norm))
    if cfg.name == "adamw":
        parts.append(
            optax.adamw(
                schedule, b1=cfg.b1, b2=cfg.b2, weight_decay=cfg.weight_decay
            )
        )
    elif cfg.name == "adam":
        parts.append(optax.adam(schedule, b1=cfg.b1, b2=cfg.b2))
    elif cfg.name == "sgd":
        parts.append(optax.sgd(schedule))
    else:
        raise ValueError(f"unknown optimizer {cfg.name}")
    return optax.chain(*parts)
