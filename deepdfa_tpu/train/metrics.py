"""Streaming classification metrics (host-side accumulation).

Replaces the reference's torchmetrics collections
(DDFA/code_gnn/models/base_module.py:35-68): accuracy / precision / recall /
F1, positive- and negative-subset breakdowns, PR curves (raw + binned) and
the confusion matrix. Device code only emits (probs, labels, mask); all
accumulation is numpy so it composes with any batch/shard layout.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class BinaryClassificationMetrics:
    threshold: float = 0.5
    store_curve: bool = True

    def __post_init__(self):
        self.reset()

    def reset(self) -> None:
        self.tp = self.fp = self.tn = self.fn = 0
        self._probs: list[np.ndarray] = []
        self._labels: list[np.ndarray] = []

    def update(self, probs, labels, mask=None) -> None:
        probs = np.asarray(probs, np.float32).reshape(-1)
        labels = np.asarray(labels, np.float32).reshape(-1)
        if mask is not None:
            keep = np.asarray(mask, bool).reshape(-1)
            probs, labels = probs[keep], labels[keep]
        preds = probs >= self.threshold
        pos = labels >= 0.5
        self.tp += int(np.sum(preds & pos))
        self.fp += int(np.sum(preds & ~pos))
        self.fn += int(np.sum(~preds & pos))
        self.tn += int(np.sum(~preds & ~pos))
        if self.store_curve:
            self._probs.append(probs)
            self._labels.append(labels)

    @property
    def count(self) -> int:
        return self.tp + self.fp + self.tn + self.fn

    def compute(self) -> dict[str, float]:
        tp, fp, tn, fn = self.tp, self.fp, self.tn, self.fn
        total = max(tp + fp + tn + fn, 1)
        prec = tp / (tp + fp) if tp + fp else 0.0
        rec = tp / (tp + fn) if tp + fn else 0.0
        f1 = 2 * prec * rec / (prec + rec) if prec + rec else 0.0
        return {
            "acc": (tp + tn) / total,
            "precision": prec,
            "recall": rec,
            "f1": f1,
            "pos_acc": rec,
            "neg_acc": tn / (tn + fp) if tn + fp else 0.0,
            "pred_pos_rate": (tp + fp) / total,
            "label_pos_rate": (tp + fn) / total,
        }

    def confusion_matrix(self) -> np.ndarray:
        return np.array([[self.tn, self.fp], [self.fn, self.tp]], np.int64)

    def pr_curve(self, num_points: int = 200) -> dict[str, np.ndarray]:
        """PR pairs over score thresholds (binned like the reference's
        pr_binned.csv so curve size is independent of dataset size)."""
        if not self._probs:
            return {"precision": np.array([]), "recall": np.array([]), "thresholds": np.array([])}
        probs = np.concatenate(self._probs)
        labels = np.concatenate(self._labels) >= 0.5
        thresholds = np.linspace(0.0, 1.0, num_points, endpoint=False)
        prec = np.zeros(num_points)
        rec = np.zeros(num_points)
        npos = max(labels.sum(), 1)
        for i, t in enumerate(thresholds):
            preds = probs >= t
            tp = np.sum(preds & labels)
            prec[i] = tp / max(preds.sum(), 1)
            rec[i] = tp / npos
        return {"precision": prec, "recall": rec, "thresholds": thresholds}


def classification_report(m: BinaryClassificationMetrics) -> str:
    c = m.compute()
    cm = m.confusion_matrix()
    lines = [
        f"examples: {m.count}",
        f"confusion matrix [[tn fp][fn tp]]: {cm.tolist()}",
    ]
    lines += [f"{k:>15}: {v:.4f}" for k, v in c.items()]
    return "\n".join(lines)
