"""Dataset readers for the real corpora (Big-Vul/MSR, Devign).

Reproduces the reference's dataset construction semantics
(DDFA/sastvd/helpers/datasets.py:139-292 bigvul):
- comment stripping on before/after functions,
- per-example diff -> removed/added lines (in-process difflib instead of
  one `git diff --no-index` subprocess per row, git.py:12-165),
- vulnerable-row post-filters: drop no-change vulns, abnormal endings,
  mod_prop >= 0.7, functions of <= 5 lines,
- split partitions from a splits csv (id,split) or a seeded random split
  (datasets.py ds_partition / bigvul_rand_splits.csv).

Outputs the pipeline's `Example` rows; everything downstream (extraction,
vocab, batching) is dataset-agnostic.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Iterable, Sequence

import numpy as np
import pandas as pd

from deepdfa_tpu.data.diffs import labeled_diff, split_lines
from deepdfa_tpu.data.pipeline import Example
from deepdfa_tpu.frontend.tokens import strip_comments


def _clean_func(code: str) -> str:
    return strip_comments(str(code))


def _keep_vulnerable(
    before: str, removed: set[int], added: set[int]
) -> bool:
    if not removed and not added:
        return False  # vulnerable but no change recorded
    tail = before.strip()[-1:] if before.strip() else ""
    if tail not in ("}", ";"):
        return False
    if before.strip()[-2:] == ");":
        return False
    # line counts use the same \n-only numbering as the diff labels
    n_before = len(split_lines(before))
    n_lines = max(n_before, 1)
    mod_prop = (len(removed) + len(added)) / n_lines
    if mod_prop >= 0.7:
        return False
    if n_before <= 5:
        return False
    return True


def _read_with_ids(csv_path: str | Path, columns: tuple[str, ...]) -> pd.DataFrame:
    """Read selected Big-Vul csv columns with the row index normalized to
    an `id` column (pandas surfaces the unnamed index as 'Unnamed: 0')."""
    df = pd.read_csv(
        csv_path, usecols=lambda c: c in ("Unnamed: 0",) + columns
    )
    if "Unnamed: 0" in df.columns:
        return df.rename(columns={"Unnamed: 0": "id"})
    return df.reset_index().rename(columns={"index": "id"})


def read_bigvul(
    csv_path: str | Path,
    sample: int | None = None,
) -> list[Example]:
    """MSR_data_cleaned.csv schema: func_before/func_after/vul columns,
    row index as example id."""
    df = _read_with_ids(csv_path, ("func_before", "func_after", "vul"))
    if sample:
        # stratified sample-mode corpus (sample_MSR_data.py:6-16: equal
        # seeded draws per class — head() on a ~6%-vul dataset would
        # yield almost no positives)
        per_class = max(1, sample // 2)
        parts = [
            g.sample(min(per_class, len(g)), random_state=0)
            for _, g in df.groupby(df.vul != 0)
        ]
        # original row order, not class-0-first: order-sensitive
        # downstream consumers (seeded random splits over row order)
        # must see a stable corpus for the same flags
        df = pd.concat(parts).sort_index()
    out: list[Example] = []
    for row in df.itertuples(index=False):
        before = _clean_func(row.func_before)
        after = _clean_func(row.func_after)
        vul = int(row.vul)
        if vul:
            # one xdiff pass serves the vuln filters AND the labels
            removed, added, guards = labeled_diff(before, after)
            if not _keep_vulnerable(before, removed, added):
                continue
            lines = frozenset(removed if removed else guards)
        else:
            lines = frozenset()
        out.append(
            Example(id=int(row.id), code=before, label=float(vul), vuln_lines=lines)
        )
    return out


def read_devign(json_path: str | Path, sample: int | None = None) -> list[Example]:
    """Devign function.json: [{"func": ..., "target": 0/1}, ...] — graph
    labels only (no line annotations in this dataset)."""
    rows = json.loads(Path(json_path).read_text())
    if sample:
        rows = rows[:sample]
    return [
        Example(
            id=i,
            code=_clean_func(r["func"]),
            label=float(r.get("target", 0)),
            vuln_lines=frozenset(),
        )
        for i, r in enumerate(rows)
    ]


def read_mutated(
    jsonl_path: str | Path,
    base_examples: Sequence[Example],
    flip: bool = False,
) -> list[Example]:
    """Mutated Big-Vul variants (reference datasets.py:104-126 mutated()):
    jsonl rows {"idx": <base id>, "source": ..., "target": ...} inner-join
    the base dataset on id; the mutated code replaces `before` (the
    `target` field, or `source` for the "_flip" subdatasets) while labels
    and line annotations carry over from the base example."""
    by_id = {e.id: e for e in base_examples}
    key = "source" if flip else "target"
    out: list[Example] = []
    with open(jsonl_path, encoding="utf-8") as f:
        for line in f:
            row = json.loads(line)
            base = by_id.get(int(row["idx"]))
            if base is None:
                continue  # inner join: only examples with mutated code
            import dataclasses as _dc

            out.append(_dc.replace(base, code=_clean_func(row[key])))
    return out


def read_dbgbench(csv_path: str | Path, sample: int | None = None) -> list[Example]:
    """DbgBench real-bug eval corpus (reference paper Table 8; unixcoder
    linevul_main.py:142-145: func column is `code`, label derives from the
    source filename column `c` — buggy unless it contains "patched")."""
    df = pd.read_csv(csv_path)
    if sample:
        df = df.head(sample)
    out: list[Example] = []
    for i, row in enumerate(df.itertuples(index=False)):
        label = float("patched" not in str(row.c))
        out.append(
            Example(
                id=int(getattr(row, "id", i)),
                code=_clean_func(row.code),
                label=label,
                vuln_lines=frozenset(),
            )
        )
    return out


def read_splits_csv(path: str | Path) -> dict[int, str]:
    """splits csv: columns (id/idx, split) with split in train/val/test
    (the reference's linevul_splits.csv / bigvul_rand_splits.csv shape)."""
    df = pd.read_csv(path)
    id_col = next(c for c in ("id", "idx", "example_id", df.columns[0]) if c in df.columns)
    split_col = next(c for c in ("split", "partition", df.columns[-1]) if c in df.columns)
    mapping = {}
    rename = {"valid": "val", "holdout": "test"}
    for row in df.itertuples(index=False):
        s = str(getattr(row, split_col)).lower()
        mapping[int(getattr(row, id_col))] = rename.get(s, s)
    return mapping


def cross_project_splits(
    csv_path: str | Path,
    test_projects: Sequence[str] | None = None,
    holdout_frac: float = 0.2,
    seed: int = 0,
) -> dict[int, str]:
    """Project-disjoint splits for cross-project generalization evaluation
    (reference paper Table 7: train on some projects, test on unseen ones).

    Reads the `project` column of the Big-Vul csv. Either pass explicit
    test_projects, or a seeded holdout_frac of projects becomes test and
    the rest splits train/val 90/10 by example."""
    df = _read_with_ids(csv_path, ("project",))
    projects = sorted(df["project"].dropna().unique().tolist())
    rng = np.random.default_rng(seed)
    if test_projects is None:
        n_test = max(1, int(len(projects) * holdout_frac))
        test_projects = [
            projects[i] for i in rng.permutation(len(projects))[:n_test]
        ]
    test_set = set(test_projects)
    out: dict[int, str] = {}
    for row in df.itertuples(index=False):
        if row.project in test_set:
            out[int(row.id)] = "test"
        else:
            out[int(row.id)] = "train" if rng.random() < 0.9 else "val"
    return out


def random_splits(
    ids: Iterable[int], seed: int = 0, train: float = 0.8, val: float = 0.1
) -> dict[int, str]:
    ids = np.array(sorted(ids))
    rng = np.random.default_rng(seed)
    perm = rng.permutation(len(ids))
    n_train = int(len(ids) * train)
    n_val = int(len(ids) * val)
    out: dict[int, str] = {}
    for k, i in enumerate(perm):
        split = "train" if k < n_train else ("val" if k < n_train + n_val else "test")
        out[int(ids[i])] = split
    return out


def partition(
    examples: list[Example], splits: dict[int, str]
) -> dict[str, list[Example]]:
    out: dict[str, list[Example]] = {"train": [], "val": [], "test": []}
    for ex in examples:
        s = splits.get(ex.id)
        if s in out:
            out[s].append(ex)
    # split disjointness is an invariant the reference asserts at runtime
    # (datamodule.py:74-78); ids are unique by construction here
    return out
