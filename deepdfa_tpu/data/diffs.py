"""Changed-line labeling from before/after function pairs.

The reference shells out to `git diff --no-index` per example and parses
hunk headers (DDFA/sastvd/helpers/git.py:12-165) to get added/removed line
numbers; statement labels are then "removed lines + lines data/control
dependent on added lines" (evaluate.py:194-236). Here the diff is computed
in-process with difflib (same line-level semantics, no subprocess per
example), and the dependency closure runs on the CPG built by our frontend.
"""

from __future__ import annotations

import difflib


def diff_lines(before: str, after: str) -> tuple[set[int], set[int]]:
    """(removed_lines_in_before, added_lines_in_after), 1-based."""
    b = before.splitlines()
    a = after.splitlines()
    removed: set[int] = set()
    added: set[int] = set()
    sm = difflib.SequenceMatcher(a=b, b=a, autojunk=False)
    for tag, i1, i2, j1, j2 in sm.get_opcodes():
        if tag in ("replace", "delete"):
            removed.update(range(i1 + 1, i2 + 1))
        if tag in ("replace", "insert"):
            added.update(range(j1 + 1, j2 + 1))
    return removed, added


def guarded_lines(before: str, after: str) -> set[int]:
    """Before-lines immediately following a pure insertion point.

    When a fix only *adds* lines (e.g. inserting a null/bounds check), the
    vulnerable statement is the one the insertion guards — the first
    before-line after the insertion point. This is the cheap first-order
    version of the reference's 'lines dependent on added lines' closure
    (evaluate.py:194-236); the full CPG-based dependency closure is in
    eval/statements.py.
    """
    b = before.splitlines()
    a = after.splitlines()
    sm = difflib.SequenceMatcher(a=b, b=a, autojunk=False)
    out: set[int] = set()
    for tag, i1, i2, j1, j2 in sm.get_opcodes():
        if tag == "insert" and i1 < len(b):
            out.add(i1 + 1)
    return out


def vulnerable_lines(before: str, after: str) -> set[int]:
    """Line labels for the *before* version: removed/changed lines plus
    lines guarded by pure insertions."""
    removed, added = diff_lines(before, after)
    if removed:
        return removed
    return guarded_lines(before, after)
