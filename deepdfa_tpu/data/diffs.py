"""Changed-line labeling from before/after function pairs.

The reference shells out to `git diff --no-index` per example and parses
hunk headers (DDFA/sastvd/helpers/git.py:12-165) to get added/removed line
numbers; statement labels are then "removed lines + lines data/control
dependent on added lines" (evaluate.py:194-236). Here the diff is computed
in-process (no subprocess per example) with git's own xdiff pipeline,
freshly implemented: bidirectional middle-snake Myers (xdl_split
semantics, so the CHOICE among equally minimal edit scripts matches
git's) followed by change compaction — group sliding with merge,
alignment to the other file's changes, and the indent-heuristic split
scoring that is on by default in modern git (xdl_change_compact). Together with the
xdl_cleanup_records pre-discard and the xdl_split cost heuristics, hunk
boundaries — and therefore vuln-line labels — match `git diff
--no-index` byte-for-byte on EVERY fuzz corpus: 297/297 adversarial
duplicate-line soups, 297/297 indented soups, 297/297 C-like edit
scripts, 29/29 thousand-line rewrites (scripts/fuzz_diffs_vs_git.py,
docs/diff_fuzz_report.json; goldens in tests/goldens/diff_labels.json).
"""

from __future__ import annotations


_BIG = 1 << 60
_SNAKE_CNT = 20  # XDL_SNAKE_CNT
_HEUR_MIN_COST = 256  # XDL_HEUR_MIN_COST
_K_HEUR = 4  # XDL_K_HEUR
_MAX_COST_MIN = 256  # XDL_MAX_COST_MIN


def _bogosqrt(n: int) -> int:
    """git's shift-based integer sqrt overestimate (xdl_bogosqrt)."""
    i = 1
    while n > 0:
        i <<= 1
        n >>= 2
    return i


def _xdl_split(
    a: list[str],
    b: list[str],
    off1: int,
    lim1: int,
    off2: int,
    lim2: int,
    need_min: bool,
    mxcost: int,
) -> tuple[int, int, bool, bool]:
    """Find a split point the way git's xdl_split does; returns
    (i1, i2, min_lo, min_hi) — the flags say whether each half must be
    searched minimally (they come back False for a heuristic split).

    Simultaneous forward and backward D-path searches return the first
    overlap; matching git's direction interleaving and tie-breaks
    (forward prefers the deletion-first diagonal on ties, backward the
    mirror) is what makes the chosen edit script — among several equally
    minimal ones — identical to git's on ambiguous duplicate-heavy
    input. Because `git diff` never sets XDF_NEED_MINIMAL, its two
    cost heuristics apply and are replicated here: past _HEUR_MIN_COST
    edits a long-snake diagonal that is "interesting enough"
    (_K_HEUR x cost) is taken immediately, and past `mxcost` the
    furthest-reaching diagonals are taken outright."""
    dmin, dmax = off1 - lim2, lim1 - off2
    fmid, bmid = off1 - off2, lim1 - lim2
    odd = (fmid - bmid) & 1
    kvdf = {fmid: off1, fmid - 1: -1, fmid + 1: -1}
    kvdb = {bmid: lim1, bmid - 1: _BIG, bmid + 1: _BIG}
    fmin = fmax = fmid
    bmin = bmax = bmid
    ec = 1
    while True:
        got_snake = False
        # one forward sweep
        if fmin > dmin:
            fmin -= 1
            kvdf[fmin - 1] = -1
        else:
            fmin += 1
        if fmax < dmax:
            fmax += 1
            kvdf[fmax + 1] = -1
        else:
            fmax -= 1
        for d in range(fmax, fmin - 1, -2):
            if kvdf[d - 1] >= kvdf[d + 1]:
                i1 = kvdf[d - 1] + 1
            else:
                i1 = kvdf[d + 1]
            prev1 = i1
            i2 = i1 - d
            while i1 < lim1 and i2 < lim2 and a[i1] == b[i2]:
                i1 += 1
                i2 += 1
            if i1 - prev1 > _SNAKE_CNT:
                got_snake = True
            kvdf[d] = i1
            if odd and bmin <= d <= bmax and kvdb.get(d, _BIG) <= i1:
                return i1, i2, True, True
        # one backward sweep
        if bmin > dmin:
            bmin -= 1
            kvdb[bmin - 1] = _BIG
        else:
            bmin += 1
        if bmax < dmax:
            bmax += 1
            kvdb[bmax + 1] = _BIG
        else:
            bmax -= 1
        for d in range(bmax, bmin - 1, -2):
            if kvdb[d - 1] < kvdb[d + 1]:
                i1 = kvdb[d - 1]
            else:
                i1 = kvdb[d + 1] - 1
            prev1 = i1
            i2 = i1 - d
            while i1 > off1 and i2 > off2 and a[i1 - 1] == b[i2 - 1]:
                i1 -= 1
                i2 -= 1
            if prev1 - i1 > _SNAKE_CNT:
                got_snake = True
            kvdb[d] = i1
            if not odd and fmin <= d <= fmax and i1 <= kvdf.get(d, -1):
                return i1, i2, True, True

        if need_min:
            ec += 1
            continue

        # heuristic 1 (git's "got_snake" path): past _HEUR_MIN_COST
        # edits, sample current diagonals for one whose distance from
        # the corner (minus its off-mid penalty) is interesting enough
        # (> _K_HEUR x cost) and which sits at the end of a >=_SNAKE_CNT
        # snake; split there, searching only the snake-adjacent half
        # minimally.
        if got_snake and ec > _HEUR_MIN_COST:
            best = 0
            spl_i1 = spl_i2 = 0
            for d in range(fmax, fmin - 1, -2):
                dd = d - fmid if d > fmid else fmid - d
                i1 = kvdf[d]
                i2 = i1 - d
                v = (i1 - off1) + (i2 - off2) - dd
                if (
                    v > _K_HEUR * ec
                    and v > best
                    and off1 + _SNAKE_CNT <= i1 < lim1
                    and off2 + _SNAKE_CNT <= i2 < lim2
                ):
                    k = 1
                    while a[i1 - k] == b[i2 - k]:
                        if k == _SNAKE_CNT:
                            best = v
                            spl_i1 = i1 - k
                            spl_i2 = i2 - k
                            break
                        k += 1
            if best > 0:
                return spl_i1, spl_i2, True, False

            best = 0
            for d in range(bmax, bmin - 1, -2):
                dd = d - bmid if d > bmid else bmid - d
                i1 = kvdb[d]
                i2 = i1 - d
                v = (lim1 - i1) + (lim2 - i2) - dd
                if (
                    v > _K_HEUR * ec
                    and v > best
                    and off1 < i1 <= lim1 - _SNAKE_CNT
                    and off2 < i2 <= lim2 - _SNAKE_CNT
                ):
                    k = 0
                    while a[i1 + k] == b[i2 + k]:
                        if k == _SNAKE_CNT - 1:
                            best = v
                            spl_i1 = i1
                            spl_i2 = i2
                            break
                        k += 1
            if best > 0:
                return spl_i1, spl_i2, False, True

        # heuristic 2: enough is enough — past mxcost take the
        # furthest-reaching forward or backward diagonal outright
        if ec >= mxcost:
            fbest = fbest1 = -1
            for d in range(fmax, fmin - 1, -2):
                i1 = min(kvdf[d], lim1)
                i2 = i1 - d
                if lim2 < i2:
                    i1 = lim2 + d
                    i2 = lim2
                if fbest < i1 + i2:
                    fbest = i1 + i2
                    fbest1 = i1
            bbest = bbest1 = _BIG
            for d in range(bmax, bmin - 1, -2):
                i1 = max(off1, kvdb[d])
                i2 = i1 - d
                if i2 < off2:
                    i1 = off2 + d
                    i2 = off2
                if i1 + i2 < bbest:
                    bbest = i1 + i2
                    bbest1 = i1
            if (lim1 + lim2) - bbest < fbest - (off1 + off2):
                return fbest1, fbest - fbest1, True, False
            return bbest1, bbest - bbest1, False, True
        ec += 1


_KPDIS_RUN = 4  # XDL_KPDIS_RUN
_MAX_EQLIMIT = 1024  # XDL_MAX_EQLIMIT
_SIMSCAN_WINDOW = 100  # XDL_SIMSCAN_WINDOW


def _clean_mmatch(dis: dict[int, int], i: int, s: int, e: int) -> bool:
    """git's xdl_clean_mmatch: discard a too-frequent line (dis[i]==2)
    only when it sits inside a run of no-match (0) / multi-match (2)
    lines with no-match lines on BOTH sides and the run is dominated by
    no-match lines. s/e are inclusive window bounds."""
    if i - s > _SIMSCAN_WINDOW:
        s = i - _SIMSCAN_WINDOW
    if e - i > _SIMSCAN_WINDOW:
        e = i + _SIMSCAN_WINDOW
    r, rdis0, rpdis0 = 1, 0, 1
    while i - r >= s:
        d = dis[i - r]
        if d == 0:
            rdis0 += 1
        elif d == 2:
            rpdis0 += 1
        else:
            break
        r += 1
    if rdis0 == 0:
        return False
    r, rdis1, rpdis1 = 1, 0, 1
    while i + r <= e:
        d = dis[i + r]
        if d == 0:
            rdis1 += 1
        elif d == 2:
            rpdis1 += 1
        else:
            break
        r += 1
    if rdis1 == 0:
        return False
    rdis0 += rdis1
    rpdis0 += rpdis1
    return rpdis0 * _KPDIS_RUN < rpdis0 + rdis0


def _cleanup_records(
    a: list[str], b: list[str], a0: int, a1: int, b0: int, b1: int
) -> tuple[list[int], list[int]]:
    """git's xdl_cleanup_records: within the trimmed windows, pre-discard
    lines that have no match in the other file or appear there too often
    (>= bogosqrt of the file size); discarded lines are marked changed
    upfront and excluded from the Myers search. Occurrence counts span
    the WHOLE other file (the classifier counts every record), while the
    keep/discard scan runs over the trimmed window only. Returns the
    surviving indices per side."""
    from collections import Counter

    count_in_b = Counter(b)
    count_in_a = Counter(a)

    def classify(lines, lo, hi, other_counts, mlim) -> dict[int, int]:
        dis = {}
        for i in range(lo, hi):
            nm = other_counts.get(lines[i], 0)
            dis[i] = 0 if nm == 0 else (2 if nm >= mlim else 1)
        return dis

    def keep(lines, lo, hi, dis) -> list[int]:
        return [
            i
            for i in range(lo, hi)
            if dis[i] == 1
            or (dis[i] == 2 and not _clean_mmatch(dis, i, lo, hi - 1))
        ]

    mlim_a = min(_bogosqrt(len(a)), _MAX_EQLIMIT)
    mlim_b = min(_bogosqrt(len(b)), _MAX_EQLIMIT)
    dis_a = classify(a, a0, a1, count_in_b, mlim_a)
    dis_b = classify(b, b0, b1, count_in_a, mlim_b)
    return keep(a, a0, a1, dis_a), keep(b, b0, b1, dis_b)


def _xdl_diff_core(
    a: list[str], b: list[str], rchg1: list[bool], rchg2: list[bool],
    mxcost: int,
) -> None:
    """xdl_recs_cmp divide-and-conquer over (a, b), marking rchg in
    place; explicit work stack (Big-Vul functions can be thousands of
    lines; Python recursion is not). Each box is first shrunk over its
    boundary snakes, then split at the middle snake and both halves
    pushed."""
    stack = [(0, len(a), 0, len(b), False)]
    while stack:
        off1, lim1, off2, lim2, need_min = stack.pop()
        while off1 < lim1 and off2 < lim2 and a[off1] == b[off2]:
            off1 += 1
            off2 += 1
        while off1 < lim1 and off2 < lim2 and a[lim1 - 1] == b[lim2 - 1]:
            lim1 -= 1
            lim2 -= 1
        if off1 == lim1:
            for j in range(off2, lim2):
                rchg2[j] = True
        elif off2 == lim2:
            for i in range(off1, lim1):
                rchg1[i] = True
        else:
            i1, i2, min_lo, min_hi = _xdl_split(
                a, b, off1, lim1, off2, lim2, need_min, mxcost
            )
            stack.append((off1, i1, off2, i2, min_lo))
            stack.append((i1, lim1, i2, lim2, min_hi))


def _xdl_diff(a: list[str], b: list[str]) -> tuple[list[bool], list[bool]]:
    """git-identical diff: changed-line maps for (a, b).

    Pipeline order matches xdl_optimize_ctxs + xdl_do_diff: trim common
    head/tail (xdl_trim_ends), pre-discard no-match / too-frequent lines
    (xdl_cleanup_records — they are marked changed and excluded from the
    search), run the middle-snake divide-and-conquer over the surviving
    subsequences, and map the changed flags back. mxcost is bogosqrt of
    the SURVIVING diagonal count (xdl_do_diff uses nreff), floored at
    _MAX_COST_MIN."""
    rchg1 = [False] * len(a)
    rchg2 = [False] * len(b)
    a0, b0 = 0, 0
    a1, b1 = len(a), len(b)
    while a0 < a1 and b0 < b1 and a[a0] == b[b0]:
        a0 += 1
        b0 += 1
    while a0 < a1 and b0 < b1 and a[a1 - 1] == b[b1 - 1]:
        a1 -= 1
        b1 -= 1
    keep_a, keep_b = _cleanup_records(a, b, a0, a1, b0, b1)
    kept_a, kept_b = set(keep_a), set(keep_b)
    for i in range(a0, a1):
        if i not in kept_a:
            rchg1[i] = True
    for j in range(b0, b1):
        if j not in kept_b:
            rchg2[j] = True
    ra = [a[i] for i in keep_a]
    rb = [b[j] for j in keep_b]
    sub1 = [False] * len(ra)
    sub2 = [False] * len(rb)
    mxcost = max(_bogosqrt(len(ra) + len(rb) + 3), _MAX_COST_MIN)
    _xdl_diff_core(ra, rb, sub1, sub2, mxcost)
    for k, i in enumerate(keep_a):
        if sub1[k]:
            rchg1[i] = True
    for k, j in enumerate(keep_b):
        if sub2[k]:
            rchg2[j] = True
    return rchg1, rchg2


def _insert_positions(bchg: list[bool], achg: list[bool]) -> set[int]:
    """0-based before-file positions where after-file insertions land,
    derived from the two changed maps by walking the matched unchanged
    pairs (the common subsequence is identical in both files)."""
    ins: set[int] = set()
    i = j = 0
    while j < len(achg) or i < len(bchg):
        if i < len(bchg) and bchg[i]:
            i += 1
            continue
        if j < len(achg) and achg[j]:
            ins.add(i)
            j += 1
            continue
        i += 1
        j += 1
    return ins


# ---------------------------------------------------------------------------
# git-xdiff change compaction.
#
# Raw Myers output is ambiguous wherever a changed run can slide over
# identical neighbouring lines; git normalizes it in xdl_change_compact
# (xdiff/xdiffi.c): each group of changed lines is slid up/down as far as
# it goes (merging with groups it touches), then its final position is
# chosen by (1) aligning with a changed group in the OTHER file if any
# slide position does, else (2) the indent-heuristic split score (on by
# default since git 2.14, diff.indentHeuristic), else (3) left fully
# slid down. This is a fresh Python implementation of that published
# algorithm so vuln-line labels match `git diff --no-index` byte-for-byte
# even on duplicate-line runs (the round-3 adversarial tail).

_MAX_SLIDING = 100  # INDENT_HEURISTIC_MAX_SLIDING: bound the split scan
_MAX_INDENT = 200
_MAX_BLANKS = 20
_START_OF_FILE_PENALTY = 1
_END_OF_FILE_PENALTY = 21
_TOTAL_BLANK_WEIGHT = -30
_POST_BLANK_WEIGHT = 6
_RELATIVE_INDENT_PENALTY = -4
_RELATIVE_INDENT_WITH_BLANK_PENALTY = 10
_RELATIVE_OUTDENT_PENALTY = 24
_RELATIVE_OUTDENT_WITH_BLANK_PENALTY = 17
_RELATIVE_DEDENT_PENALTY = 23
_RELATIVE_DEDENT_WITH_BLANK_PENALTY = 17
_INDENT_WEIGHT = 60


def _get_indent(line: str) -> int:
    """Visual indent of a line (tab = next multiple of 8); -1 if blank.
    Matches git's get_indent: OTHER whitespace (\\r \\f \\v — ASCII
    isspace, e.g. the \\r of a CRLF file after \\n-splitting) is skipped
    without advancing the column, and an all-whitespace line is blank."""
    ret = 0
    for ch in line:
        if ch == " ":
            ret += 1
        elif ch == "\t":
            ret += 8 - ret % 8
        elif ch in "\r\f\v\n":
            pass  # whitespace, but not indentation
        else:
            return min(ret, _MAX_INDENT)
        if ret >= _MAX_INDENT:
            return _MAX_INDENT
    return -1


def _score_split(lines: list[str], split: int, score: list[int]) -> None:
    """Accumulate the badness of splitting just before lines[split] into
    score = [effective_indent, penalty] (both smaller = better)."""
    n = len(lines)
    if split >= n:
        end_of_file = True
        indent = -1
    else:
        end_of_file = False
        indent = _get_indent(lines[split])

    pre_blank, pre_indent = 0, -1
    for i in range(split - 1, -1, -1):
        pre_indent = _get_indent(lines[i])
        if pre_indent != -1:
            break
        pre_blank += 1
        if pre_blank == _MAX_BLANKS:
            pre_indent = 0
            break

    post_blank, post_indent = 0, -1
    for i in range(split + 1, n):
        post_indent = _get_indent(lines[i])
        if post_indent != -1:
            break
        post_blank += 1
        if post_blank == _MAX_BLANKS:
            post_indent = 0
            break

    if pre_indent == -1 and pre_blank == 0:
        score[1] += _START_OF_FILE_PENALTY
    if end_of_file:
        score[1] += _END_OF_FILE_PENALTY

    this_post_blank = 1 + post_blank if indent == -1 else 0
    total_blank = pre_blank + this_post_blank
    score[1] += _TOTAL_BLANK_WEIGHT * total_blank
    score[1] += _POST_BLANK_WEIGHT * this_post_blank

    eff_indent = indent if indent != -1 else post_indent
    any_blanks = total_blank != 0
    score[0] += eff_indent

    if eff_indent == -1 or pre_indent == -1:
        pass
    elif eff_indent > pre_indent:
        score[1] += (
            _RELATIVE_INDENT_WITH_BLANK_PENALTY
            if any_blanks
            else _RELATIVE_INDENT_PENALTY
        )
    elif eff_indent == pre_indent:
        pass
    elif post_indent != -1 and post_indent > eff_indent:
        # outdented vs predecessor but followed by deeper code: likely
        # the start of a block (e.g. an `else`)
        score[1] += (
            _RELATIVE_OUTDENT_WITH_BLANK_PENALTY
            if any_blanks
            else _RELATIVE_OUTDENT_PENALTY
        )
    else:
        # probably the end of a block
        score[1] += (
            _RELATIVE_DEDENT_WITH_BLANK_PENALTY
            if any_blanks
            else _RELATIVE_DEDENT_PENALTY
        )


def _score_cmp(s1: list[int], s2: list[int]) -> int:
    cmp_indents = (s1[0] > s2[0]) - (s1[0] < s2[0])
    return _INDENT_WEIGHT * cmp_indents + (s1[1] - s2[1])


class _Group:
    """[start, end) run of changed lines; empty groups sit between the
    matched unchanged lines, which is what keeps the two files' group
    cursors in lockstep (each file has the same unchanged-line count)."""

    __slots__ = ("start", "end")

    def __init__(self, chg: list[bool]):
        self.start = 0
        e = 0
        while e < len(chg) and chg[e]:
            e += 1
        self.end = e


def _group_next(chg: list[bool], g: _Group) -> bool:
    if g.end == len(chg):
        return False
    g.start = g.end + 1
    e = g.start
    while e < len(chg) and chg[e]:
        e += 1
    g.end = e
    return True


def _group_previous(chg: list[bool], g: _Group) -> bool:
    if g.start == 0:
        return False
    g.end = g.start - 1
    s = g.end
    while s > 0 and chg[s - 1]:
        s -= 1
    g.start = s
    return True


def _group_slide_up(chg: list[bool], lines: list[str], g: _Group) -> bool:
    if g.start > 0 and lines[g.start - 1] == lines[g.end - 1]:
        g.start -= 1
        g.end -= 1
        chg[g.start] = True
        chg[g.end] = False
        while g.start > 0 and chg[g.start - 1]:
            g.start -= 1
        return True
    return False


def _group_slide_down(chg: list[bool], lines: list[str], g: _Group) -> bool:
    if g.end < len(lines) and lines[g.start] == lines[g.end]:
        chg[g.start] = False
        chg[g.end] = True
        g.start += 1
        g.end += 1
        while g.end < len(lines) and chg[g.end]:
            g.end += 1
        return True
    return False


def _change_compact(
    chg: list[bool], lines: list[str], ochg: list[bool]
) -> None:
    """Normalize `chg` in place the way xdl_change_compact does; `ochg`
    is the other file's (read-only) changed map, used to align sliding
    groups with the other side's changes."""
    g = _Group(chg)
    go = _Group(ochg)
    while True:
        if g.end != g.start:
            while True:
                groupsize = g.end - g.start
                end_matching_other = -1
                while _group_slide_up(chg, lines, g):
                    if not _group_previous(ochg, go):
                        raise AssertionError("group sync broken sliding up")
                earliest_end = g.end
                if go.end > go.start:
                    end_matching_other = g.end
                while _group_slide_down(chg, lines, g):
                    if not _group_next(ochg, go):
                        raise AssertionError("group sync broken sliding down")
                    if go.end > go.start:
                        end_matching_other = g.end
                if groupsize == g.end - g.start:
                    break  # no merge happened; the slide range is final
            if g.end == earliest_end:
                pass  # no freedom to shift
            elif end_matching_other != -1:
                # align with the last other-file change group any slide
                # position lines up with
                while go.end == go.start:
                    if not _group_slide_up(chg, lines, g):
                        raise AssertionError("match disappeared")
                    if not _group_previous(ochg, go):
                        raise AssertionError("sync broken sliding to match")
            else:
                # indent heuristic: a group implies two splits (above and
                # below it); score every reachable shift and keep the
                # best, later shifts winning ties
                groupsize = g.end - g.start
                best_shift = -1
                best_score = [0, 0]
                for shift in range(
                    max(earliest_end, g.end - _MAX_SLIDING), g.end + 1
                ):
                    score = [0, 0]
                    _score_split(lines, shift - groupsize, score)
                    _score_split(lines, shift, score)
                    if best_shift == -1 or _score_cmp(score, best_score) <= 0:
                        best_score = score
                        best_shift = shift
                while g.end > best_shift:
                    if not _group_slide_up(chg, lines, g):
                        raise AssertionError("best shift unreachable")
                    if not _group_previous(ochg, go):
                        raise AssertionError("sync broken sliding to best")
        if not _group_next(chg, g):
            break
        if not _group_next(ochg, go):
            raise AssertionError("group sync broken advancing")


def split_lines(text: str) -> list[str]:
    """Split exactly as git (and this framework's C lexer) does: on
    ``\\n`` only — form feeds, vertical tabs, NEL, U+2028 etc. are LINE
    CONTENT; str.splitlines would break on them and shift every
    subsequent label — with no phantom empty line after a trailing
    newline. EVERY consumer that numbers source lines (label producers,
    token-line assignment, line-count filters) must use this so line
    coordinates agree end to end."""
    lines = text.split("\n")
    if lines and lines[-1] == "":
        lines.pop()
    return lines


def _compacted_changes(
    b: list[str],
    a: list[str],
    raw: tuple[list[bool], list[bool]] | None = None,
) -> tuple[list[bool], list[bool]]:
    """Myers + git-identical compaction of both sides; returns the two
    changed-line maps (before, after). Pass precomputed `raw` maps to
    reuse an earlier _xdl_diff (they are copied, not mutated)."""
    bchg, achg = _xdl_diff(b, a) if raw is None else (
        list(raw[0]), list(raw[1])
    )
    # git compacts xdf1 then xdf2, each against the other's current state
    _change_compact(bchg, b, achg)
    _change_compact(achg, a, bchg)
    return bchg, achg


def diff_lines(before: str, after: str) -> tuple[set[int], set[int]]:
    """(removed_lines_in_before, added_lines_in_after), 1-based."""
    b = split_lines(before)
    a = split_lines(after)
    bchg, achg = _compacted_changes(b, a)
    return (
        {i + 1 for i, c in enumerate(bchg) if c},
        {j + 1 for j, c in enumerate(achg) if c},
    )


def guarded_lines(before: str, after: str) -> set[int]:
    """Before-lines immediately following a pure insertion point.

    When a fix only *adds* lines (e.g. inserting a null/bounds check), the
    vulnerable statement is the one the insertion guards — the first
    before-line after the insertion point. This is the cheap first-order
    version of the reference's 'lines dependent on added lines' closure
    (evaluate.py:194-236); the full CPG-based dependency closure is in
    eval/statements.py.
    """
    b = split_lines(before)
    a = split_lines(after)
    raw = _xdl_diff(b, a)
    return _guards_from(b, a, raw)


def _guards_from(
    b: list[str],
    a: list[str],
    raw: tuple[list[bool], list[bool]],
    bchg: list[bool] | None = None,
) -> set[int]:
    insert_at = _insert_positions(raw[0], raw[1])
    # PURE insertions only: an insertion adjacent to a removed line is the
    # insert half of a replacement, whose label is the removed line itself.
    # Adjacency is judged against BOTH the raw Myers removed set (which is
    # where a replacement's delete half actually sits) and the compacted
    # set diff_lines reports (so a guard line can never collide with a
    # line already labeled removed — ADVICE r3).
    if bchg is None:
        bchg, _achg = _compacted_changes(b, a, raw=raw)
    removed = {i for i, c in enumerate(raw[0]) if c} | {
        i for i, c in enumerate(bchg) if c
    }
    return {
        pos + 1
        for pos in insert_at
        if pos < len(b) and pos not in removed and (pos - 1) not in removed
    }


def labeled_diff(before: str, after: str) -> tuple[set[int], set[int], set[int]]:
    """(removed_before, added_after, guarded_before), 1-based, in ONE
    Myers pass + one compaction. The single entry point for per-example
    label computation: dataset readers need removed+added (vuln filters)
    AND the labels, and Big-Vul functions run to thousands of lines."""
    b = split_lines(before)
    a = split_lines(after)
    raw = _xdl_diff(b, a)
    bchg, achg = _compacted_changes(b, a, raw=raw)
    removed = {i + 1 for i, c in enumerate(bchg) if c}
    added = {j + 1 for j, c in enumerate(achg) if c}
    guards = _guards_from(b, a, raw, bchg=bchg)
    return removed, added, guards


def vulnerable_lines(before: str, after: str) -> set[int]:
    """Line labels for the *before* version: removed/changed lines plus
    lines guarded by pure insertions."""
    removed, _added, guards = labeled_diff(before, after)
    return removed if removed else guards
