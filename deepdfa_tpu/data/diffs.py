"""Changed-line labeling from before/after function pairs.

The reference shells out to `git diff --no-index` per example and parses
hunk headers (DDFA/sastvd/helpers/git.py:12-165) to get added/removed line
numbers; statement labels are then "removed lines + lines data/control
dependent on added lines" (evaluate.py:194-236). Here the diff is computed
in-process (no subprocess per example) with the same Myers algorithm git
uses, so hunk boundaries — and therefore vuln-line labels — match git's on
ambiguous inputs where difflib's Ratcliff-Obershelp picks a different
minimal edit (e.g. adjacent-line swaps). Pinned against real
`git diff --no-index` output in tests/goldens/diff_labels.json.
"""

from __future__ import annotations


def _myers(
    a: list[str], b: list[str], insert_at: set[int] | None = None
) -> tuple[set[int], set[int]]:
    """Greedy O(ND) Myers diff; (removed 0-based idx in a, added in b).
    When `insert_at` is given, it collects the 0-based a-positions where
    insertions land (for guarded_lines).

    Tie-breaking follows the classic formulation git's xdiff uses: extend
    the further-reaching path, preferring a deletion when paths tie —
    which is what makes an adjacent swap come out as -first/+later like
    git, not -later/+first like difflib.
    """
    if insert_at is None:
        insert_at = set()
    n, m = len(a), len(b)
    if n == 0 or m == 0:
        if m:
            insert_at.add(0)
        return set(range(n)), set(range(m))
    v: dict[int, int] = {1: 0}
    trace: list[dict[int, int]] = []
    final_d = -1
    for d in range(n + m + 1):
        trace.append(dict(v))
        for k in range(-d, d + 1, 2):
            if k == -d or (k != d and v.get(k - 1, -1) < v.get(k + 1, -1)):
                x = v.get(k + 1, 0)  # down: insert b line
            else:
                x = v.get(k - 1, 0) + 1  # right: delete a line
            y = x - k
            while x < n and y < m and a[x] == b[y]:
                x += 1
                y += 1
            v[k] = x
            if x >= n and y >= m:
                final_d = d
                break
        if final_d >= 0:
            break
    removed: set[int] = set()
    added: set[int] = set()
    x, y = n, m
    for d in range(final_d, 0, -1):
        pv = trace[d]
        k = x - y
        if k == -d or (k != d and pv.get(k - 1, -1) < pv.get(k + 1, -1)):
            prev_k = k + 1
        else:
            prev_k = k - 1
        prev_x = pv.get(prev_k, 0)
        prev_y = prev_x - prev_k
        # rewind the snake back to the single edit step
        while x > prev_x and y > prev_y and x > 0 and y > 0 and a[x - 1] == b[y - 1]:
            x -= 1
            y -= 1
        if x == prev_x:
            added.add(prev_y)  # insertion of b[prev_y], at a-position prev_x
            insert_at.add(prev_x)
        else:
            removed.add(prev_x)  # deletion of a[prev_x]
        x, y = prev_x, prev_y
    return removed, added


def _slide_up(changed: set[int], lines: list[str]) -> set[int]:
    """git-xdiff-style compaction: a run of changed lines that is free to
    slide (the line just above the run equals the run's last line) is
    reported at its UPPERMOST position — e.g. deleting one of three
    identical `step();` lines marks the first, as git does."""
    out: set[int] = set()
    runs: list[list[int]] = []
    for i in sorted(changed):
        if runs and i == runs[-1][-1] + 1:
            runs[-1].append(i)
        else:
            runs.append([i])
    for run in runs:
        start, end = run[0], run[-1]
        while start > 0 and (start - 1) not in changed and lines[start - 1] == lines[end]:
            start -= 1
            end -= 1
        out.update(range(start, end + 1))
    return out


def diff_lines(before: str, after: str) -> tuple[set[int], set[int]]:
    """(removed_lines_in_before, added_lines_in_after), 1-based."""
    b = before.splitlines()
    a = after.splitlines()
    removed, added = _myers(b, a)
    removed = _slide_up(removed, b)
    added = _slide_up(added, a)
    return {i + 1 for i in removed}, {j + 1 for j in added}


def guarded_lines(before: str, after: str) -> set[int]:
    """Before-lines immediately following a pure insertion point.

    When a fix only *adds* lines (e.g. inserting a null/bounds check), the
    vulnerable statement is the one the insertion guards — the first
    before-line after the insertion point. This is the cheap first-order
    version of the reference's 'lines dependent on added lines' closure
    (evaluate.py:194-236); the full CPG-based dependency closure is in
    eval/statements.py.
    """
    b = before.splitlines()
    a = after.splitlines()
    insert_at: set[int] = set()
    removed, _ = _myers(b, a, insert_at)
    # PURE insertions only: an insertion adjacent to a removed line is the
    # insert half of a replacement, whose label is the removed line itself
    return {
        pos + 1
        for pos in insert_at
        if pos < len(b) and pos not in removed and (pos - 1) not in removed
    }


def vulnerable_lines(before: str, after: str) -> set[int]:
    """Line labels for the *before* version: removed/changed lines plus
    lines guarded by pure insertions."""
    removed, added = diff_lines(before, after)
    if removed:
        return removed
    return guarded_lines(before, after)
