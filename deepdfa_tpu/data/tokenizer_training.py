"""Byte-level BPE tokenizer training on a code corpus.

The reference trains Salesforce-style BPE vocabularies with the
`tokenizers` library (CodeT5/tokenizer/*.py); this produces the same
vocab.json + merges.txt artifacts, which `data.tokenizer.BpeTokenizer`
(and HF tokenizers) load directly. Special tokens follow the RoBERTa
frame the combined models expect.
"""

from __future__ import annotations

from pathlib import Path
from typing import Iterable

SPECIAL_TOKENS = ["<s>", "<pad>", "</s>", "<unk>", "<mask>"]


def train_bpe(
    corpus: Iterable[str],
    out_dir: str | Path,
    vocab_size: int = 32000,
    min_frequency: int = 2,
    prefix: str = "bpe_tokenizer",
) -> tuple[Path, Path]:
    """Train byte-level BPE over in-memory code strings; writes
    `<prefix>-vocab.json` + `<prefix>-merges.txt` into out_dir and returns
    their paths."""
    from tokenizers import ByteLevelBPETokenizer

    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    tok = ByteLevelBPETokenizer()
    tok.train_from_iterator(
        corpus,
        vocab_size=vocab_size,
        min_frequency=min_frequency,
        special_tokens=SPECIAL_TOKENS,
    )
    tok.save_model(str(out_dir), prefix)
    return out_dir / f"{prefix}-vocab.json", out_dir / f"{prefix}-merges.txt"
