"""Byte-level BPE tokenizer training on a code corpus.

The reference trains Salesforce-style BPE vocabularies with the
`tokenizers` library (CodeT5/tokenizer/*.py); this produces the same
vocab.json + merges.txt artifacts, which `data.tokenizer.BpeTokenizer`
(and HF tokenizers) load directly. Special tokens follow the RoBERTa
frame the combined models expect.
"""

from __future__ import annotations

from pathlib import Path
from typing import Iterable

SPECIAL_TOKENS = ["<s>", "<pad>", "</s>", "<unk>", "<mask>"]


def train_bpe(
    corpus: Iterable[str],
    out_dir: str | Path,
    vocab_size: int = 32000,
    min_frequency: int = 2,
    prefix: str = "bpe_tokenizer",
) -> tuple[Path, Path]:
    """Train byte-level BPE over in-memory code strings; writes
    `<prefix>-vocab.json` + `<prefix>-merges.txt` into out_dir and returns
    their paths."""
    from tokenizers import ByteLevelBPETokenizer

    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    tok = ByteLevelBPETokenizer()
    tok.train_from_iterator(
        corpus,
        vocab_size=vocab_size,
        min_frequency=min_frequency,
        special_tokens=SPECIAL_TOKENS,
    )
    tok.save_model(str(out_dir), prefix)
    return out_dir / f"{prefix}-vocab.json", out_dir / f"{prefix}-merges.txt"


WORD_LEVEL_SPECIALS = ["[UNK]", "[CLS]", "[SEP]", "[PAD]", "[MASK]"]


def train_word_level(
    corpus: Iterable[str],
    out_path: str | Path,
    vocab_size: int = 50000,
    min_frequency: int = 1,
) -> Path:
    """Train a whitespace word-level tokenizer; writes one tokenizer.json.

    Asset parity with the reference's
    LineVul/linevul/word_level_tokenizer/wordlevel.json (HF `tokenizers`
    WordLevel model, Whitespace pre-tokenizer, BERT-style special tokens
    [UNK]/[CLS]/[SEP]/[PAD]/[MASK] at ids 0-4) — used by LineVul's
    `--use_word_level_tokenizer` path."""
    from tokenizers import Tokenizer
    from tokenizers.models import WordLevel
    from tokenizers.pre_tokenizers import Whitespace
    from tokenizers.trainers import WordLevelTrainer

    out_path = Path(out_path)
    out_path.parent.mkdir(parents=True, exist_ok=True)
    tok = Tokenizer(WordLevel(unk_token="[UNK]"))
    tok.pre_tokenizer = Whitespace()
    trainer = WordLevelTrainer(
        vocab_size=vocab_size,
        min_frequency=min_frequency,
        special_tokens=WORD_LEVEL_SPECIALS,
    )
    tok.train_from_iterator(corpus, trainer)
    tok.save(str(out_path))
    return out_path
