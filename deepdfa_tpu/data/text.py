"""Text(+graph) batches for the combined transformer models.

The collator implements the index-join bridge (reference:
flowgnn_dataset.get_indices + keep_idx row-dropping,
DDFA/sastvd/linevd/dataset.py:63-76, linevul_main.py:194-197) with static
shapes: text row i aligns with graph slot i; rows with no extracted graph
get `has_graph=False` and a zeroed graph embedding instead of being
dropped.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping, Sequence

import jax
import numpy as np

from deepdfa_tpu.graphs.batch import GraphSpec, pack
from deepdfa_tpu.graphs.batch import GraphBatch


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class TextBatch:
    input_ids: jax.Array  # [B, T] int32
    labels: jax.Array  # [B] int32
    row_mask: jax.Array  # [B] bool (False = padding row)
    has_graph: jax.Array  # [B] bool
    graphs: GraphBatch  # num_graphs == B, graph i <-> text row i


_EMPTY = GraphSpec(
    graph_id=-1,
    node_feats=np.zeros((1, 4), np.int32),
    node_vuln=np.zeros((1,), np.int32),
    edge_src=np.zeros((0,), np.int32),
    edge_dst=np.zeros((0,), np.int32),
    label=0.0,
)


def collate(
    token_ids: np.ndarray,  # [n, T]
    labels: Sequence[int],
    example_ids: Sequence[int],
    graphs_by_id: Mapping[int, GraphSpec],
    batch_rows: int,
    node_budget: int,
    edge_budget: int,
    pad_id: int = 1,
) -> TextBatch:
    """Build one static-shape TextBatch (n <= batch_rows).

    pad_id must match the encoder's pad convention (RoBERTa family: 1,
    T5 family: 0) — padding rows are filled with it and the encoders
    derive their attention masks from it."""
    n = len(labels)
    if n > batch_rows:
        raise ValueError(f"{n} rows > batch_rows {batch_rows}")
    T = token_ids.shape[1]
    ids = np.full((batch_rows, T), pad_id, np.int32)
    ids[:n] = token_ids
    lab = np.zeros((batch_rows,), np.int32)
    lab[:n] = np.asarray(labels, np.int32)
    row_mask = np.zeros((batch_rows,), bool)
    row_mask[:n] = True
    has_graph = np.zeros((batch_rows,), bool)
    specs: list[GraphSpec] = []
    # aggregate budgets across the whole batch: rows whose graph doesn't
    # fit (individually OR cumulatively) degrade to has_graph=False — the
    # reference's row-dropping (keep_idx) analog, never a crash
    n_used = batch_rows  # every row holds >= the 1-node _EMPTY placeholder
    e_used = batch_rows  # + its self loop
    for i in range(batch_rows):
        if i < n and example_ids[i] in graphs_by_id:
            g = graphs_by_id[example_ids[i]]
            dn = g.num_nodes - _EMPTY.num_nodes
            de = (g.num_edges + g.num_nodes) - (
                _EMPTY.num_edges + _EMPTY.num_nodes
            )
            if n_used + dn <= node_budget and e_used + de <= edge_budget:
                specs.append(g)
                has_graph[i] = True
                n_used += dn
                e_used += de
                continue
        specs.append(_EMPTY)
    gb = pack(specs, batch_rows, node_budget, edge_budget)
    return TextBatch(
        input_ids=ids,
        labels=lab,
        row_mask=row_mask,
        has_graph=has_graph,
        graphs=gb,
    )


def collate_shards(
    token_ids: np.ndarray,
    labels: Sequence[int],
    example_ids: Sequence[int],
    graphs_by_id: Mapping[int, GraphSpec],
    num_shards: int,
    rows_per_shard: int,
    node_budget: int,
    edge_budget: int,
    pad_id: int = 1,
) -> TextBatch:
    """Shard rows round-robin and stack shard batches on a leading dp axis."""
    n = len(labels)
    if n > num_shards * rows_per_shard:
        raise ValueError(
            f"{n} rows > {num_shards} x {rows_per_shard}"
        )
    shards = []
    for s in range(num_shards):
        sel = list(range(s, n, num_shards))[:rows_per_shard]
        shards.append(
            collate(
                token_ids[sel],
                [labels[i] for i in sel],
                [example_ids[i] for i in sel],
                graphs_by_id,
                rows_per_shard,
                node_budget,
                edge_budget,
                pad_id=pad_id,
            )
        )
    stacked = jax.tree.map(lambda *xs: np.stack(xs, axis=0), *shards)
    return stacked
