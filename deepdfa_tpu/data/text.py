"""Text(+graph) batches for the combined transformer models.

The collator implements the index-join bridge (reference:
flowgnn_dataset.get_indices + keep_idx row-dropping,
DDFA/sastvd/linevd/dataset.py:63-76, linevul_main.py:194-197) with static
shapes: text row i aligns with graph slot i; rows with no extracted graph
get `has_graph=False` and a zeroed graph embedding instead of being
dropped.

Sequence-length bucketing (docs/input_pipeline.md): Big-Vul function
lengths are lognormal (median ~14 statements) while the LineVul recipe
pads every row to a fixed 512 tokens — most transformer FLOPs attend
over padding. `plan_bucketed_batches` assigns each row to the smallest
configured power-of-two bucket edge that fits its real length, and sizes
each batch by a TOKEN budget (`rows x T <= budget`) so short buckets run
proportionally more rows at roughly constant activation memory. Packing
a plan goes through the same `collate_shards` as the fixed-length path,
so per-row semantics (graph alignment, has_graph budget degrade) are
identical by construction; only the pad target and row count change.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Iterator, Mapping, Sequence

import jax
import numpy as np

from deepdfa_tpu.core.config import PAD_ID_BY_FAMILY
from deepdfa_tpu.graphs.batch import GraphSpec, pack
from deepdfa_tpu.graphs.batch import GraphBatch


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class TextBatch:
    input_ids: jax.Array  # [B, T] int32
    labels: jax.Array  # [B] int32
    row_mask: jax.Array  # [B] bool (False = padding row)
    has_graph: jax.Array  # [B] bool
    graphs: GraphBatch  # num_graphs == B, graph i <-> text row i


#: TextBatch's own array leaves (the nested GraphBatch leaves are
#: graphs/batch.py:ARRAY_FIELDS) — the serialization order shared by the
#: packed-batch cache and the shared-memory packer (data/packed_cache.py,
#: data/mp_pack.py)
TEXT_ARRAY_FIELDS = ("input_ids", "labels", "row_mask", "has_graph")


_EMPTY = GraphSpec(
    graph_id=-1,
    node_feats=np.zeros((1, 4), np.int32),
    node_vuln=np.zeros((1,), np.int32),
    edge_src=np.zeros((0,), np.int32),
    edge_dst=np.zeros((0,), np.int32),
    label=0.0,
)


def collate(
    token_ids: np.ndarray,  # [n, T]
    labels: Sequence[int],
    example_ids: Sequence[int],
    graphs_by_id: Mapping[int, GraphSpec],
    batch_rows: int,
    node_budget: int,
    edge_budget: int,
    pad_id: int = PAD_ID_BY_FAMILY["roberta"],
) -> TextBatch:
    """Build one static-shape TextBatch (n <= batch_rows).

    pad_id must match the encoder's pad convention — padding rows are
    filled with it and the encoders derive their attention masks from
    it. Both sides default to the shared `PAD_ID_BY_FAMILY` table
    (core/config.py) so they cannot drift apart."""
    n = len(labels)
    if n > batch_rows:
        raise ValueError(f"{n} rows > batch_rows {batch_rows}")
    T = token_ids.shape[1]
    ids = np.full((batch_rows, T), pad_id, np.int32)
    ids[:n] = token_ids
    lab = np.zeros((batch_rows,), np.int32)
    lab[:n] = np.asarray(labels, np.int32)
    row_mask = np.zeros((batch_rows,), bool)
    row_mask[:n] = True
    has_graph = np.zeros((batch_rows,), bool)
    specs: list[GraphSpec] = []
    # aggregate budgets across the whole batch: rows whose graph doesn't
    # fit (individually OR cumulatively) degrade to has_graph=False — the
    # reference's row-dropping (keep_idx) analog, never a crash
    n_used = batch_rows  # every row holds >= the 1-node _EMPTY placeholder
    e_used = batch_rows  # + its self loop
    for i in range(batch_rows):
        if i < n and example_ids[i] in graphs_by_id:
            g = graphs_by_id[example_ids[i]]
            dn = g.num_nodes - _EMPTY.num_nodes
            de = (g.num_edges + g.num_nodes) - (
                _EMPTY.num_edges + _EMPTY.num_nodes
            )
            if n_used + dn <= node_budget and e_used + de <= edge_budget:
                specs.append(g)
                has_graph[i] = True
                n_used += dn
                e_used += de
                continue
        specs.append(_EMPTY)
    gb = pack(specs, batch_rows, node_budget, edge_budget)
    return TextBatch(
        input_ids=ids,
        labels=lab,
        row_mask=row_mask,
        has_graph=has_graph,
        graphs=gb,
    )


def collate_shards(
    token_ids: np.ndarray,
    labels: Sequence[int],
    example_ids: Sequence[int],
    graphs_by_id: Mapping[int, GraphSpec],
    num_shards: int,
    rows_per_shard: int,
    node_budget: int,
    edge_budget: int,
    pad_id: int = PAD_ID_BY_FAMILY["roberta"],
) -> TextBatch:
    """Shard rows round-robin and stack shard batches on a leading dp axis."""
    n = len(labels)
    if n > num_shards * rows_per_shard:
        raise ValueError(
            f"{n} rows > {num_shards} x {rows_per_shard}"
        )
    shards = []
    for s in range(num_shards):
        sel = list(range(s, n, num_shards))[:rows_per_shard]
        shards.append(
            collate(
                token_ids[sel],
                [labels[i] for i in sel],
                [example_ids[i] for i in sel],
                graphs_by_id,
                rows_per_shard,
                node_budget,
                edge_budget,
                pad_id=pad_id,
            )
        )
    stacked = jax.tree.map(lambda *xs: np.stack(xs, axis=0), *shards)
    return stacked


# ---------------------------------------------------------------------------
# sequence-length bucketing


def token_lengths(token_ids: np.ndarray, pad_id: int) -> np.ndarray:
    """[n] real (unpadded) length per row of a right-padded id matrix.

    Rows are right-padded with `pad_id` (the tokenizer contract), so the
    real length is the index of the last non-pad token + 1; an all-pad
    row has length 0."""
    ids = np.asarray(token_ids)
    nonpad = ids != pad_id
    tail = np.argmax(nonpad[:, ::-1], axis=1)
    return np.where(
        nonpad.any(axis=1), ids.shape[1] - tail, 0
    ).astype(np.int64)


def batch_token_counts(
    input_ids: np.ndarray, row_mask: np.ndarray, pad_id: int
) -> tuple[int, int, int]:
    """(real, padded, rows) for one batch: non-pad tokens in VALID rows,
    total token slots (the full static shape — padding rows are device
    compute too), and valid rows. The train loops feed these into
    `PipelineStats.add_tokens` so epoch records report real-token
    throughput and padding waste."""
    ids = np.asarray(input_ids)
    mask = np.asarray(row_mask, bool)
    real = int(((ids != pad_id) & mask[..., None]).sum())
    return real, int(ids.size), int(mask.sum())


def lengths_for(
    token_ids_by_id: Mapping[int, np.ndarray],
    example_ids: Sequence[int],
    pad_id: int,
) -> list[int]:
    """Real token length per selected example, in selection order.

    One vectorized `token_lengths` call over the stacked matrix when the
    rows share a width (the tokenizer pads every row to max_length, so
    they normally do) — a per-row loop over a Big-Vul-scale corpus pays
    ~180k numpy dispatches per epoch start otherwise."""
    if not len(example_ids):
        return []
    rows = [np.asarray(token_ids_by_id[i]) for i in example_ids]
    if len({r.shape[0] for r in rows}) == 1:
        return [int(n) for n in token_lengths(np.stack(rows), pad_id)]
    return [int(token_lengths(r[None], pad_id)[0]) for r in rows]


def rows_for_bucket(seq_len: int, token_budget: int, num_shards: int) -> int:
    """Rows PER SHARD a `token_budget` allows at bucket edge `seq_len`
    (`rows x T <= budget`, budget split over dp shards; at least 1 row
    per shard so a tight budget degrades to small batches, never zero).

    The ONE definition of the batch-sizing formula — the planner, the
    trainer's warmup signatures, and the benches all call it, so a
    change cannot desynchronize compile signatures from real batches."""
    return max(1, int(token_budget) // (int(seq_len) * max(1, num_shards)))


@dataclasses.dataclass(frozen=True)
class TextBatchPlan:
    """Collation recipe for one bucketed batch: which examples, padded to
    which bucket edge, at which (token-budget-derived) row count.

    Planning is cheap bookkeeping over row lengths; `collate_plan` is the
    numpy-heavy materialization — the same plan/pack split as
    graphs/batch.py:BatchPlan, shared by the inline collater, the
    process-pool packer (data/mp_pack.py:TextMpPacker) and the
    packed-batch cache builder, so every path is bit-identical by
    construction."""

    example_ids: tuple[int, ...]
    seq_len: int
    rows_per_shard: int
    num_shards: int
    node_budget: int
    edge_budget: int


def plan_bucketed_batches(
    lengths: Sequence[int] | np.ndarray,
    example_ids: Sequence[int],
    buckets: Sequence[int],
    token_budget: int,
    num_shards: int,
    node_budget: int,
    edge_budget: int,
    stats: dict | None = None,
) -> Iterator[TextBatchPlan]:
    """Assign rows to length buckets and emit token-budget-sized plans.

    Each row goes to the smallest bucket edge >= its real length (order
    within a bucket is arrival order; a bucket flushes when it reaches
    its `rows_for_bucket` capacity, and partial buckets flush ascending
    at the end — fully deterministic in the input order, which keeps the
    stream cache-replayable). A row longer than the largest bucket is a
    configuration error and raises loudly.

    stats (optional dict) receives: "batches", "rows", "real_tokens",
    "padded_tokens" (rows x bucket edge, summed) and "by_bucket"
    ({edge: rows}) — final once the generator is exhausted.
    """
    buckets = tuple(int(b) for b in buckets)
    if not buckets or list(buckets) != sorted(set(buckets)):
        raise ValueError(
            f"seq_buckets must be ascending unique edges, got {buckets}"
        )
    if buckets[0] < 2:
        raise ValueError(f"bucket edge {buckets[0]} < 2 is meaningless")
    lengths = np.asarray(lengths, np.int64)
    if len(lengths) != len(example_ids):
        raise ValueError(
            f"{len(lengths)} lengths vs {len(example_ids)} example_ids"
        )
    if stats is None:
        stats = {}
    stats.update(
        batches=0, rows=0, real_tokens=0, padded_tokens=0,
        by_bucket={b: 0 for b in buckets},
    )

    capacity = {
        b: rows_for_bucket(b, token_budget, num_shards) * num_shards
        for b in buckets
    }
    pending: dict[int, list[int]] = {b: [] for b in buckets}

    def emit(edge: int) -> TextBatchPlan:
        ids = pending[edge]
        pending[edge] = []
        stats["batches"] += 1
        stats["rows"] += len(ids)
        stats["by_bucket"][edge] += len(ids)
        # padded tokens count the FULL static shape (capacity x edge):
        # padding rows are device compute too, and the waste fraction
        # must indict them
        stats["padded_tokens"] += capacity[edge] * edge
        return TextBatchPlan(
            tuple(ids), edge, capacity[edge] // num_shards, num_shards,
            node_budget, edge_budget,
        )

    edges = np.asarray(buckets, np.int64)
    for eid, ln in zip(example_ids, lengths):
        ln = int(ln)
        if ln > buckets[-1]:
            raise ValueError(
                f"example {eid}: real token length {ln} exceeds the "
                f"largest bucket edge {buckets[-1]} — add a bucket >= "
                f"the tokenizer max_length (data.seq_buckets)"
            )
        edge = int(edges[np.searchsorted(edges, max(ln, 1))])
        pending[edge].append(int(eid))
        stats["real_tokens"] += ln
        if len(pending[edge]) == capacity[edge]:
            yield emit(edge)
    for edge in buckets:
        if pending[edge]:
            yield emit(edge)


def _fit_width(row: np.ndarray, seq_len: int, pad_id: int) -> np.ndarray:
    row = np.asarray(row, np.int32)
    if row.shape[0] >= seq_len:
        return row[:seq_len]
    out = np.full((seq_len,), pad_id, np.int32)
    out[: row.shape[0]] = row
    return out


def collate_plan(
    plan: TextBatchPlan,
    token_ids_by_id: Mapping[int, np.ndarray],
    labels_by_id: Mapping[int, int],
    graphs_by_id: Mapping[int, GraphSpec],
    pad_id: int = PAD_ID_BY_FAMILY["roberta"],
) -> TextBatch:
    """Materialize one bucketed plan through the standard collater.

    Rows slice to the bucket edge — the planner guarantees every real
    token fits, so the slice only drops trailing padding and the
    (example_id, label, unpadded-token) multiset is preserved exactly.
    Graph alignment and has_graph budget degrade are `collate_shards`'s
    own semantics, unchanged."""
    ids = plan.example_ids
    if ids:
        tok = np.stack(
            [_fit_width(token_ids_by_id[i], plan.seq_len, pad_id) for i in ids]
        )
    else:
        tok = np.zeros((0, plan.seq_len), np.int32)
    return collate_shards(
        tok,
        [int(labels_by_id[i]) for i in ids],
        list(ids),
        graphs_by_id,
        num_shards=plan.num_shards,
        rows_per_shard=plan.rows_per_shard,
        node_budget=plan.node_budget,
        edge_budget=plan.edge_budget,
        pad_id=pad_id,
    )


def bucketed_collate_batches(
    token_ids_by_id: Mapping[int, np.ndarray],
    labels_by_id: Mapping[int, int],
    example_ids: Sequence[int],
    graphs_by_id: Mapping[int, GraphSpec],
    buckets: Sequence[int],
    token_budget: int,
    num_shards: int,
    node_budget: int,
    edge_budget: int,
    pad_id: int = PAD_ID_BY_FAMILY["roberta"],
    lengths: Sequence[int] | None = None,
    stats: dict | None = None,
) -> Iterable[TextBatch]:
    """Plan + collate in one pass (the inline, no-pool path)."""
    if lengths is None:
        lengths = lengths_for(token_ids_by_id, example_ids, pad_id)
    for plan in plan_bucketed_batches(
        lengths, example_ids, buckets, token_budget, num_shards,
        node_budget, edge_budget, stats=stats,
    ):
        yield collate_plan(
            plan, token_ids_by_id, labels_by_id, graphs_by_id, pad_id
        )
