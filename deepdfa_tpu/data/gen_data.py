"""Readers + batching for the generation / clone tasks (CodeT5 family).

Format-parity ports of the reference's example readers
(CodeT5/_utils.py:168-310) so existing task files drop in unchanged:

- summarize: jsonl with code_tokens/docstring_tokens (+optional idx)
- translate / refine: "src_file,trg_file" paired line files
- concode: jsonl with nl/code
- defect-as-generation: jsonl with code/target (target rendered as the
  strings "true"/"false", _utils.py:convert_examples_to_features)
- clone: tab-separated url pairs + sibling data.jsonl id->func map

Batches are static-shape [B, S]/[B, T] int arrays with a row mask; the
shard variant stacks a leading dp axis exactly like data/text.py.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Sequence

import jax
import numpy as np


@dataclasses.dataclass(frozen=True)
class GenExample:
    idx: int | str
    source: str
    target: str


@dataclasses.dataclass(frozen=True)
class CloneExample:
    source: str
    target: str
    label: int
    url1: str
    url2: str


def _collapse_ws(s: str) -> str:
    return " ".join(s.split())


def read_summarize_examples(filename: str, data_num: int = -1) -> list[GenExample]:
    examples = []
    with open(filename, encoding="utf-8") as f:
        for idx, line in enumerate(f):
            js = json.loads(line.strip())
            code = _collapse_ws(" ".join(js["code_tokens"]).replace("\n", " "))
            nl = _collapse_ws(" ".join(js["docstring_tokens"]).replace("\n", ""))
            examples.append(GenExample(idx=js.get("idx", idx), source=code, target=nl))
            if idx + 1 == data_num:
                break
    return examples


def _read_paired(filename: str, data_num: int) -> list[GenExample]:
    src_file, trg_file = filename.split(",")
    examples = []
    with open(src_file) as f1, open(trg_file) as f2:
        for idx, (line1, line2) in enumerate(zip(f1, f2)):
            examples.append(
                GenExample(idx=idx, source=line1.strip(), target=line2.strip())
            )
            if idx + 1 == data_num:
                break
    return examples


def read_translate_examples(filename: str, data_num: int = -1) -> list[GenExample]:
    return _read_paired(filename, data_num)


def read_refine_examples(filename: str, data_num: int = -1) -> list[GenExample]:
    return _read_paired(filename, data_num)


def read_concode_examples(filename: str, data_num: int = -1) -> list[GenExample]:
    examples = []
    with open(filename) as f:
        for idx, line in enumerate(f):
            js = json.loads(line)
            examples.append(
                GenExample(idx=idx, source=js["nl"].strip(), target=js["code"].strip())
            )
            if idx + 1 == data_num:
                break
    return examples


def read_defect_gen_examples(filename: str, data_num: int = -1) -> list[GenExample]:
    """Defect detection as generation: target is 'true'/'false'
    (_utils.py:260-279 + convert_examples_to_features label rendering)."""
    examples = []
    with open(filename, encoding="utf-8") as f:
        for idx, line in enumerate(f):
            js = json.loads(line.strip())
            target = {0: "false", 1: "true"}[int(js["target"])]
            examples.append(
                GenExample(
                    idx=js.get("idx", idx),
                    source=_collapse_ws(js["code"]),
                    target=target,
                )
            )
            if idx + 1 == data_num:
                break
    return examples


def read_clone_examples(filename: str, data_num: int = -1) -> list[CloneExample]:
    """Tab-separated 'url1\turl2\tlabel' rows; code bodies come from the
    sibling data.jsonl (reference read_clone_examples, _utils.py:281-310)."""
    data_jsonl = os.path.join(os.path.dirname(filename), "data.jsonl")
    url_to_code = {}
    with open(data_jsonl) as f:
        for line in f:
            js = json.loads(line.strip())
            url_to_code[str(js["idx"])] = _collapse_ws(js["func"])

    data = []
    with open(filename) as f:
        for line in f:
            url1, url2, label = line.strip().split("\t")
            if url1 not in url_to_code or url2 not in url_to_code:
                continue
            data.append(
                CloneExample(
                    source=url_to_code[url1],
                    target=url_to_code[url2],
                    label=0 if label == "0" else 1,
                    url1=url1,
                    url2=url2,
                )
            )
            if len(data) == data_num:
                break
    return data


READERS = {
    "summarize": read_summarize_examples,
    "translate": read_translate_examples,
    "refine": read_refine_examples,
    "concode": read_concode_examples,
    "defect": read_defect_gen_examples,
}


# ---------------------------------------------------------------------------
# batching


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class GenBatch:
    source_ids: jax.Array  # [B, S] int32 (or [dp, B, S] sharded)
    target_ids: jax.Array  # [B, T] int32
    row_mask: jax.Array  # [B] bool


def collate_gen(
    source_ids: np.ndarray,
    target_ids: np.ndarray,
    batch_rows: int,
    pad_id: int = 0,
) -> GenBatch:
    n = source_ids.shape[0]
    if n > batch_rows:
        raise ValueError(f"{n} rows > batch_rows {batch_rows}")
    src = np.full((batch_rows, source_ids.shape[1]), pad_id, np.int32)
    tgt = np.full((batch_rows, target_ids.shape[1]), pad_id, np.int32)
    src[:n] = source_ids
    tgt[:n] = target_ids
    mask = np.zeros((batch_rows,), bool)
    mask[:n] = True
    return GenBatch(source_ids=src, target_ids=tgt, row_mask=mask)


def collate_gen_shards(
    source_ids: np.ndarray,
    target_ids: np.ndarray,
    num_shards: int,
    rows_per_shard: int,
    pad_id: int = 0,
) -> GenBatch:
    """Round-robin rows onto a leading dp axis (cf. data/text.py:99)."""
    n = source_ids.shape[0]
    if n > num_shards * rows_per_shard:
        raise ValueError(f"{n} rows > {num_shards} x {rows_per_shard}")
    shards = []
    for s in range(num_shards):
        sel = list(range(s, n, num_shards))[:rows_per_shard]
        shards.append(
            collate_gen(source_ids[sel], target_ids[sel], rows_per_shard, pad_id)
        )
    return jax.tree.map(lambda *xs: np.stack(xs, axis=0), *shards)


def batches_of(
    source_ids: np.ndarray,
    target_ids: np.ndarray,
    num_shards: int,
    rows_per_shard: int,
    pad_id: int = 0,
    shuffle_seed: int | None = None,
) -> list[GenBatch]:
    """Full epoch as a list of sharded GenBatches (last batch padded)."""
    n = source_ids.shape[0]
    order = np.arange(n)
    if shuffle_seed is not None:
        np.random.default_rng(shuffle_seed).shuffle(order)
    per = num_shards * rows_per_shard
    out = []
    for i in range(0, n, per):
        sel = order[i : i + per]
        out.append(
            collate_gen_shards(
                source_ids[sel], target_ids[sel], num_shards, rows_per_shard,
                pad_id,
            )
        )
    return out
