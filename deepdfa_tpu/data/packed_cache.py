"""Persistent packed-batch cache: replay fully-packed GraphBatch streams
zero-copy from disk.

Motivation (BENCH_r05): every epoch re-ran single-threaded numpy packing
over compressed npz shards behind one prefetch thread, so the host — not
the device — bounded train throughput. The fully-packed batch stream is a
pure function of (batcher schema, budgets, vocab, source graphs), so it is
cached once and every later epoch AND every re-run with the same
configuration replays it as flat, mmap-able ``.npy`` files: the OS page
cache hands batches back without touching the frontend, the packer, or
the inflate path.

Layout (one directory per cache key):

    <root>/<key>/b00000.node_feats.npy      one flat .npy per (batch, field)
    <root>/<key>/b00000.edge_src.npy
    ...
    <root>/<key>/manifest.json              written LAST -> presence marks
                                            the entry complete

Key / invalidation rules (docs/input_pipeline.md): the key is a sha256
over the batcher schema version, every packing parameter, a digest of the
source graphs (GraphStore.digest() for on-disk corpora, corpus_digest()
for in-memory ones), and the vocab digest. Any re-extraction, budget
change, or batcher-semantics bump (SCHEMA_VERSION) changes the key — stale
entries are never replayed, only orphaned (prune() collects them).

Replay is bit-identical to direct packing — same arrays, same batch order
(tests/test_packed_cache.py pins it) — so training numerics are unchanged.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import shutil
import tempfile
import time
from pathlib import Path
from typing import Callable, Iterable, Iterator, Mapping, Sequence

import numpy as np

from deepdfa_tpu.core.ioutil import with_retries
from deepdfa_tpu.data.text import (
    TEXT_ARRAY_FIELDS as _TEXT_FIELDS,
    TextBatch,
)
from deepdfa_tpu.graphs.batch import (
    ARRAY_FIELDS as _ARRAY_FIELDS,
    GraphBatch,
    GraphSpec,
)

#: bump on ANY change to pack()/plan semantics that alters the packed
#: bytes for identical inputs — it is part of every cache key
SCHEMA_VERSION = 1

logger = logging.getLogger(__name__)

#: entry dirs whose full content digests this process has already
#: verified — later epochs replay with size checks only (docs/resilience.md)
_VERIFIED: set[str] = set()


class CacheCorruption(RuntimeError):
    """A cache entry failed size/digest verification (truncated write-out
    from a killed writer, bit rot, manual tampering). `get_or_pack`
    quarantines the entry and falls through to cold packing."""


def _file_digest(path: Path, chunk: int = 1 << 20) -> tuple[int, str]:
    """(size, sha256) of a file's bytes, streamed."""
    h = hashlib.sha256()
    size = 0
    with path.open("rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            size += len(b)
            h.update(b)
    return size, h.hexdigest()


def cache_key(
    batcher: Mapping[str, object],
    source_digest: str,
    vocab_digest: str = "",
) -> str:
    """Content hash identifying one packed-batch stream.

    batcher: every parameter that shapes the stream (num_shards,
    num_graphs, node_budget, edge_budget, add_self_loops, oversized,
    selection epoch/seed, ...). Keys and values must be JSON-serializable;
    insertion order is canonicalized away.
    """
    payload = json.dumps(
        {
            "schema": SCHEMA_VERSION,
            "batcher": dict(sorted(batcher.items())),
            "source": source_digest,
            "vocab": vocab_digest,
        },
        sort_keys=True,
        default=str,
    )
    return hashlib.sha256(payload.encode()).hexdigest()[:32]


def corpus_digest(specs: Sequence[GraphSpec]) -> str:
    """Content digest of an in-memory GraphSpec corpus (cache-key source
    component when graphs never touched a GraphStore — e.g. synthetic
    benches). Hashes every array's bytes, so any feature/label/edge edit
    invalidates."""
    h = hashlib.sha256()
    h.update(len(specs).to_bytes(8, "little"))
    for g in specs:
        h.update(int(g.graph_id).to_bytes(8, "little", signed=True))
        h.update(np.float64(g.label).tobytes())
        for f in dataclasses.fields(g):
            v = getattr(g, f.name)
            if not isinstance(v, np.ndarray):
                continue
            a = np.ascontiguousarray(v)
            h.update(f.name.encode())
            h.update(str(a.dtype).encode())
            h.update(str(a.shape).encode())
            h.update(a.tobytes())
    return h.hexdigest()


def text_corpus_digest(
    token_ids_by_id: Mapping[int, np.ndarray],
    labels_by_id: Mapping[int, int],
) -> str:
    """Content digest of a tokenized text corpus (cache-key source
    component for bucketed TextBatch streams, keyed id order
    canonicalized). Hashes every row's bytes + label, so any
    re-tokenization (max_length, vocab, framing) or label edit
    invalidates. Combine with the graph-side digest for combined-model
    streams — both halves shape the packed bytes."""
    h = hashlib.sha256()
    h.update(len(token_ids_by_id).to_bytes(8, "little"))
    for i in sorted(token_ids_by_id):
        a = np.ascontiguousarray(np.asarray(token_ids_by_id[i]))
        h.update(int(i).to_bytes(8, "little", signed=True))
        h.update(int(labels_by_id[i]).to_bytes(8, "little", signed=True))
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


class PackedBatchCache:
    """A directory of packed-batch streams addressable by cache key.

    max_entries bounds the directory: finalizing a new entry evicts the
    least-recently-USED ones beyond the limit (epoch-keyed undersample
    selections write one entry per epoch, so an unbounded cache grows
    with every sweep; replay() touches the manifest so a hot entry — the
    eval split, replayed every epoch — never ages out under a stream of
    train-epoch writes). None = unbounded.
    """

    def __init__(
        self,
        root: str | Path,
        max_entries: int | None = None,
        io_retries: int = 2,
        io_backoff_s: float = 0.05,
    ):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.max_entries = max_entries
        # transient host-I/O retry policy for replay reads
        # (train.resilience.io_* config via the CLI)
        self.io_retries = int(io_retries)
        self.io_backoff_s = float(io_backoff_s)

    def entry_dir(self, key: str) -> Path:
        return self.root / key

    def has(self, key: str) -> bool:
        """True when a COMPLETE entry exists (manifest is written last)."""
        return (self.entry_dir(key) / "manifest.json").is_file()

    # -- write ---------------------------------------------------------------

    def write_through(
        self, key: str, batches: Iterable[GraphBatch | TextBatch]
    ) -> Iterator[GraphBatch | TextBatch]:
        """Yield `batches` unchanged while persisting them.

        The first epoch trains at full speed off the live packer; the
        entry becomes visible (manifest + atomic dir rename) only after
        the stream is exhausted, so an interrupted run never leaves a
        truncated entry a later run could mistake for complete. On any
        error the partial spill is removed and the error propagates.
        """
        tmp = Path(
            tempfile.mkdtemp(prefix=f".{key}-", dir=self.root)
        )
        meta: list[dict] = []
        try:
            for i, batch in enumerate(batches):
                meta.append(self._save_batch(tmp, i, batch))
                yield batch
            self._finalize(tmp, key, meta)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise

    def _save_batch(
        self, d: Path, i: int, batch: GraphBatch | TextBatch
    ) -> dict:
        if isinstance(batch, TextBatch):
            # bucketed TextBatch: its own leaves plus the nested graph
            # leaves under a "graphs." file infix; manifests tag the
            # kind so replay rebuilds the right pytree (graph-only
            # manifests predate the tag and default to "graph")
            gfields = []
            for name in _TEXT_FIELDS:
                np.save(
                    d / f"b{i:05d}.{name}.npy",
                    np.asarray(getattr(batch, name)),
                )
            g = batch.graphs
            for name in _ARRAY_FIELDS:
                v = getattr(g, name)
                if v is None:
                    continue
                gfields.append(name)
                np.save(d / f"b{i:05d}.graphs.{name}.npy", np.asarray(v))
            return {
                "kind": "text",
                "num_graphs": int(g.num_graphs),
                "fields": list(_TEXT_FIELDS),
                "graph_fields": gfields,
            }
        fields = []
        for name in _ARRAY_FIELDS:
            v = getattr(batch, name)
            if v is None:
                continue
            fields.append(name)
            np.save(d / f"b{i:05d}.{name}.npy", np.asarray(v))
        return {"num_graphs": int(batch.num_graphs), "fields": fields}

    def _finalize(self, tmp: Path, key: str, meta: list[dict]) -> None:
        # per-file size + content digest: replay verifies before serving
        # mmap'd arrays, so a shard truncated by a killed writer (or a
        # post-rename power loss — the npy data pages are not fsynced) is
        # detected and quarantined instead of replayed as garbage. The
        # files were just written, so this hashing pass reads from the
        # page cache.
        files = {
            p.name: dict(zip(("size", "sha256"), _file_digest(p)))
            for p in sorted(tmp.glob("*.npy"))
        }
        (tmp / "manifest.json").write_text(
            json.dumps(
                {
                    "schema": SCHEMA_VERSION,
                    "key": key,
                    "n_batches": len(meta),
                    "batches": meta,
                    "files": files,
                }
            )
        )
        try:
            os.replace(tmp, self.entry_dir(key))
        except OSError:
            # a concurrent writer finished the same key first — identical
            # content by construction, so discard ours
            shutil.rmtree(tmp, ignore_errors=True)
            if not self.has(key):
                raise
        self._evict(keep=key)

    def _evict(self, keep: str) -> None:
        if self.max_entries is None:
            return
        entries = []
        for k in self.keys():
            if k == keep:
                continue
            try:
                entries.append(
                    ((self.entry_dir(k) / "manifest.json").stat().st_mtime, k)
                )
            except OSError:
                continue  # concurrently pruned
        for _, k in sorted(entries)[: max(0, len(entries) + 1 - self.max_entries)]:
            shutil.rmtree(self.entry_dir(k), ignore_errors=True)

    # -- read ----------------------------------------------------------------

    def _verify(self, d: Path, manifest: Mapping) -> None:
        """Check the entry's files against the manifest's recorded sizes
        and content digests BEFORE any array is served.

        Sizes are stat'd on every replay (cheap; catches the killed-writer
        truncation). The full digest pass runs once per entry per process
        — later epochs replay without re-hashing. Entries written before
        digests existed carry no "files" block and skip verification.
        """
        files = manifest.get("files")
        if files is None:
            return
        for name, rec in files.items():
            try:
                size = (d / name).stat().st_size
            except OSError as e:
                raise CacheCorruption(f"{name}: {e}") from e
            if size != rec["size"]:
                raise CacheCorruption(
                    f"{name}: size {size} != recorded {rec['size']} "
                    f"(truncated write-out?)"
                )
        if str(d) in _VERIFIED:
            return
        for name, rec in files.items():
            _, digest = _file_digest(d / name)
            if digest != rec["sha256"]:
                raise CacheCorruption(
                    f"{name}: content digest mismatch "
                    f"({digest[:12]} != {rec['sha256'][:12]})"
                )
        _VERIFIED.add(str(d))

    def replay(
        self, key: str, mmap: bool = True
    ) -> Iterator[GraphBatch | TextBatch]:
        """Iterate a complete entry; arrays are read-only mmap views by
        default (zero-copy until device_put). Batch kind comes from the
        manifest: "text" entries rebuild the TextBatch + nested
        GraphBatch pytree; untagged entries are graph-only (they predate
        the tag). Sizes/digests are verified up front (CacheCorruption on
        mismatch); transient read errors retry with backoff."""
        d = self.entry_dir(key)
        manifest_path = d / "manifest.json"
        try:
            manifest = with_retries(
                lambda: json.loads(manifest_path.read_text()),
                retries=self.io_retries, backoff_s=self.io_backoff_s,
                what=f"cache manifest read {key}",
            )
        except FileNotFoundError:
            raise
        except (json.JSONDecodeError, OSError) as e:
            raise CacheCorruption(f"manifest.json: {e}") from e
        if manifest.get("schema") != SCHEMA_VERSION:
            raise ValueError(
                f"cache entry {key} has schema {manifest.get('schema')}, "
                f"expected {SCHEMA_VERSION} — key derivation is broken"
            )
        self._verify(d, manifest)
        try:
            os.utime(manifest_path)  # LRU stamp read by _evict
        except OSError:
            pass  # read-only cache dir: eviction degrades to write order
        mode = "r" if mmap else None

        def load(path: Path):
            try:
                return with_retries(
                    lambda: np.load(path, mmap_mode=mode),
                    retries=self.io_retries, backoff_s=self.io_backoff_s,
                    what=f"cache read {path.name}",
                )
            except FileNotFoundError:
                raise  # concurrent eviction: handled by get_or_pack
            except (ValueError, EOFError, OSError) as e:
                # np.load's header/parse failures on a damaged file
                raise CacheCorruption(f"{path.name}: {e}") from e

        for i, m in enumerate(manifest["batches"]):
            arrays = {
                name: load(d / f"b{i:05d}.{name}.npy")
                for name in m["fields"]
            }
            if m.get("kind") == "text":
                garrays = {
                    name: load(d / f"b{i:05d}.graphs.{name}.npy")
                    for name in m["graph_fields"]
                }
                yield TextBatch(
                    **{n: arrays.get(n) for n in _TEXT_FIELDS},
                    graphs=GraphBatch(
                        **{n: garrays.get(n) for n in _ARRAY_FIELDS},
                        num_graphs=m["num_graphs"],
                    ),
                )
                continue
            yield GraphBatch(
                **{n: arrays.get(n) for n in _ARRAY_FIELDS},
                num_graphs=m["num_graphs"],
            )

    def get_or_pack(
        self,
        key: str,
        builder: Callable[[], Iterable[GraphBatch | TextBatch]],
        mmap: bool = True,
    ) -> Iterator[GraphBatch | TextBatch]:
        """Replay `key` when warm; otherwise build via `builder()` and
        persist write-through. Either way the consumer sees the exact
        stream `builder()` would produce."""
        if self.has(key):
            return self._replay_or_rebuild(key, builder, mmap)
        return self.write_through(key, builder())

    def _replay_or_rebuild(
        self,
        key: str,
        builder: Callable[[], Iterable[GraphBatch]],
        mmap: bool,
    ) -> Iterator[GraphBatch]:
        """Replay, falling back to a rebuild if the entry vanishes or
        fails verification.

        A concurrent run sharing this root (e.g. NNI sweep trials) can
        evict/prune the entry between has() and the last np.load — already
        -yielded mmap views stay valid (the fd pins the pages), but the
        next file open raises FileNotFoundError. A truncated/corrupt
        entry (killed writer, bit rot) raises CacheCorruption and is
        QUARANTINED for post-mortem. Either way the stream is a pure
        function of the key, so rebuild via `builder()` and resume after
        the batches already yielded instead of killing the training run.
        """
        n = 0
        try:
            for batch in self.replay(key, mmap=mmap):
                yield batch
                n += 1
            return
        except FileNotFoundError:
            pass
        except CacheCorruption as e:
            dest = self.quarantine(key)
            logger.warning(
                "packed cache entry %s corrupt (%s); quarantined to %s "
                "and repacking cold", key, e, dest,
            )
        for i, batch in enumerate(self.write_through(key, builder())):
            if i >= n:
                yield batch

    # -- maintenance ---------------------------------------------------------

    #: quarantined entries retained for post-mortem (newest first)
    QUARANTINE_KEEP = 4

    def quarantine(self, key: str) -> Path | None:
        """Move a corrupt entry aside (bounded keep) so the next pack can
        rebuild at the key's path while the damaged bytes stay available
        for inspection. Returns the quarantine path (None when the entry
        was already gone or could not be moved)."""
        d = self.entry_dir(key)
        _VERIFIED.discard(str(d))
        if not d.exists():
            return None
        qroot = self.root / "quarantine"
        qroot.mkdir(exist_ok=True)
        dest = qroot / f"{key}-{os.getpid()}-{time.time_ns()}"
        try:
            os.replace(d, dest)
        except OSError:
            # cross-run race or odd filesystem: dropping it still unblocks
            shutil.rmtree(d, ignore_errors=True)
            return None
        def quarantined_at(p: Path) -> int:
            # os.replace preserves the entry's ORIGINAL mtime, so order
            # by the quarantine timestamp embedded in the name — an old
            # entry quarantined just now must be the newest, not the
            # first evicted
            try:
                return int(p.name.rsplit("-", 1)[-1])
            except ValueError:
                return 0

        old = sorted(qroot.iterdir(), key=quarantined_at)
        for p in old[: -self.QUARANTINE_KEEP]:
            shutil.rmtree(p, ignore_errors=True)
        return dest

    def keys(self) -> list[str]:
        # dot-prefixed dirs are in-progress write spills; _finalize
        # writes their manifest BEFORE the rename, so manifest presence
        # alone would briefly count them as (evictable) complete entries
        return sorted(
            p.name
            for p in self.root.iterdir()
            if p.is_dir()
            and not p.name.startswith(".")
            and (p / "manifest.json").is_file()
        )

    #: a dot-prefixed spill younger than this is assumed LIVE (another
    #: process mid write_through — each _save_batch touches the dir
    #: mtime); only older ones are collected as abandoned
    SPILL_TTL_SECONDS = 6 * 3600.0

    def prune(self, keep: Iterable[str] = ()) -> int:
        """Remove entries not in `keep`, plus abandoned temp spills.
        Returns the number of directories removed."""
        keep = set(keep)
        n = 0
        for p in self.root.iterdir():
            if not p.is_dir():
                continue
            if p.name.startswith("."):
                try:
                    age = time.time() - p.stat().st_mtime
                except OSError:
                    continue  # concurrently finalized or removed
                if age < self.SPILL_TTL_SECONDS:
                    continue
            elif p.name in keep:
                continue
            shutil.rmtree(p, ignore_errors=True)
            n += 1
        return n
