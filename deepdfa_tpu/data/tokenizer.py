"""Tokenizers for the transformer path.

Two implementations behind one interface:

- `BpeTokenizer`: GPT-2/RoBERTa byte-level BPE, loading standard
  vocab.json + merges.txt files from disk (the format of codebert-base and
  the reference's bundled assets, LineVul/linevul/bpe_tokenizer/). No
  network access needed — point it at local files.
- `HashTokenizer`: dependency-free deterministic fallback that buckets
  whitespace/punctuation-split tokens by hash. Used for hermetic tests and
  synthetic corpora where a pretrained vocab is meaningless.

Both produce fixed-length right-padded id arrays with <s>/</s> framing,
the shape contract of the reference's convert_examples_to_features
(LineVul/linevul/linevul_main.py:120-131).
"""

from __future__ import annotations

import json
import re
from functools import lru_cache
from pathlib import Path

import numpy as np

from deepdfa_tpu.core.config import PAD_ID_BY_FAMILY
from deepdfa_tpu.data.diffs import split_lines


class Tokenizer:
    cls_id: int
    sep_id: int
    pad_id: int
    vocab_size: int

    def encode(self, text: str, max_length: int = 512) -> np.ndarray:
        raise NotImplementedError

    def encode_with_lines(
        self, text: str, max_length: int = 512
    ) -> tuple[np.ndarray, np.ndarray]:
        """(ids, line_of_token) — 1-based source line per token, 0 for
        specials/padding. Powers line-level localization
        (eval/localize.aggregate_line_scores)."""
        raise NotImplementedError

    def batch_encode(self, texts, max_length: int = 512) -> np.ndarray:
        return np.stack([self.encode(t, max_length) for t in texts])


class HashTokenizer(Tokenizer):
    """Deterministic hash-bucket tokenizer (tests / synthetic corpora).

    Default special ids follow the RoBERTa frame (cls 0 / pad 1 / sep 2);
    pass t5_frame=True for the T5 convention (pad 0 / sep==eos 2) so the
    encoder's pad-derived attention mask and eos pooling line up."""

    _WORD = re.compile(r"[A-Za-z_][A-Za-z0-9_]*|\d+|\S")

    def __init__(self, vocab_size: int = 4096, t5_frame: bool = False):
        assert vocab_size > 8
        self.vocab_size = vocab_size
        # pad ids come from the shared family table (core/config.py) so
        # the collaters and the encoders' mask derivation agree with the
        # frames produced here by construction
        if t5_frame:
            self.pad_id = PAD_ID_BY_FAMILY["t5"]
            self.cls_id, self.sep_id, self.unk_id = 1, 2, 3
        else:
            self.pad_id = PAD_ID_BY_FAMILY["roberta"]
            self.cls_id, self.sep_id, self.unk_id = 0, 2, 3
        self._first = 4

    def encode(self, text: str, max_length: int = 512) -> np.ndarray:
        return self.encode_with_lines(text, max_length)[0]

    def encode_with_lines(self, text: str, max_length: int = 512):
        import hashlib

        ids = [self.cls_id]
        lines = [0]
        # \n-only numbering, matching the diff-label / CPG coordinates
        for lineno, line in enumerate(split_lines(text), start=1):
            for m in self._WORD.finditer(line):
                if len(ids) >= max_length - 1:
                    break
                h = int.from_bytes(
                    hashlib.blake2s(m.group().encode(), digest_size=4).digest(),
                    "little",
                )
                ids.append(self._first + h % (self.vocab_size - self._first))
                lines.append(lineno)
            if len(ids) >= max_length - 1:
                break
        ids.append(self.sep_id)
        lines.append(0)
        out = np.full((max_length,), self.pad_id, np.int32)
        out[: len(ids)] = ids[:max_length]
        out_lines = np.zeros((max_length,), np.int32)
        out_lines[: len(lines)] = lines[:max_length]
        return out, out_lines


@lru_cache()
def _bytes_to_unicode() -> dict[int, str]:
    """GPT-2 byte<->unicode table (standard byte-level BPE alphabet)."""
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("\xa1"), ord("\xac") + 1))
        + list(range(ord("\xae"), ord("\xff") + 1))
    )
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, map(chr, cs)))


try:  # exact GPT-2 pretokenizer needs unicode classes (\p{L}, \p{N})
    import regex as _regex

    _GPT2_PAT = _regex.compile(
        r"'s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+| ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+"
    )
except ImportError:  # pragma: no cover - ascii fallback
    _GPT2_PAT = re.compile(
        r"'s|'t|'re|'ve|'m|'ll|'d| ?[A-Za-z]+| ?\d+| ?[^\sA-Za-z\d]+|\s+(?!\S)|\s+"
    )


class BpeTokenizer(Tokenizer):
    """GPT-2-style byte-level BPE from vocab.json + merges.txt."""

    _PAT = _GPT2_PAT

    def __init__(self, vocab_file: str | Path, merges_file: str | Path,
                 cls_token="<s>", sep_token="</s>", pad_token="<pad>",
                 unk_token="<unk>"):
        self.vocab: dict[str, int] = json.loads(Path(vocab_file).read_text())
        merges = Path(merges_file).read_text().splitlines()
        merges = [m for m in merges if m and not m.startswith("#version")]
        self.bpe_ranks = {tuple(m.split()): i for i, m in enumerate(merges)}
        self.byte_encoder = _bytes_to_unicode()
        self.vocab_size = len(self.vocab)
        self.cls_id = self.vocab[cls_token]
        self.sep_id = self.vocab[sep_token]
        self.pad_id = self.vocab[pad_token]
        self.unk_id = self.vocab.get(unk_token, 3)
        self._cache: dict[str, list[str]] = {}

    def _bpe(self, token: str) -> list[str]:
        if token in self._cache:
            return self._cache[token]
        word = list(token)
        while len(word) > 1:
            pairs = {(word[i], word[i + 1]) for i in range(len(word) - 1)}
            best = min(pairs, key=lambda p: self.bpe_ranks.get(p, 1 << 60))
            if best not in self.bpe_ranks:
                break
            first, second = best
            new_word: list[str] = []
            i = 0
            while i < len(word):
                if (
                    i < len(word) - 1
                    and word[i] == first
                    and word[i + 1] == second
                ):
                    new_word.append(first + second)
                    i += 2
                else:
                    new_word.append(word[i])
                    i += 1
            word = new_word
        self._cache[token] = word
        return word

    def encode(self, text: str, max_length: int = 512) -> np.ndarray:
        ids = [self.cls_id]
        for chunk in self._PAT.findall(text):
            mapped = "".join(self.byte_encoder[b] for b in chunk.encode("utf-8"))
            for piece in self._bpe(mapped):
                ids.append(self.vocab.get(piece, self.unk_id))
                if len(ids) >= max_length - 1:
                    break
            if len(ids) >= max_length - 1:
                break
        ids.append(self.sep_id)
        out = np.full((max_length,), self.pad_id, np.int32)
        out[: len(ids)] = ids
        return out

    def encode_with_lines(self, text: str, max_length: int = 512):
        ids = [self.cls_id]
        lines = [0]
        pos = 0
        line = 1
        for m in self._PAT.finditer(text):
            chunk = m.group()
            line += text.count("\n", pos, m.start())
            pos = m.start()
            mapped = "".join(self.byte_encoder[b] for b in chunk.encode("utf-8"))
            for piece in self._bpe(mapped):
                if len(ids) >= max_length - 1:
                    break
                ids.append(self.vocab.get(piece, self.unk_id))
                lines.append(line)
            if len(ids) >= max_length - 1:
                break
        ids.append(self.sep_id)
        lines.append(0)
        out = np.full((max_length,), self.pad_id, np.int32)
        out[: len(ids)] = ids
        out_lines = np.zeros((max_length,), np.int32)
        out_lines[: len(lines)] = lines
        return out, out_lines
