"""End-to-end preprocessing: C source -> model-ready GraphSpec.

Mirrors the reference pipeline stages (DDFA/scripts/preprocess.sh):
  prepare (clean + line labels) -> getgraphs (CPG extraction) ->
  dbize (node/edge tables) -> abstract_dataflow (stage 1+2) ->
  dbize_absdf (vocab indexing)
but runs hermetically on the built-in frontend, in-process, with
multiprocessing fan-out for corpus-scale extraction.

The model graph is the reference's: CPG nodes that carry a line number and
participate in CFG edges, reindexed densely (feature_extraction,
DDFA/sastvd/linevd/utils.py:28-76 with graph_type="cfg"); per-node vuln
labels come from changed-line sets (dbize.py:35-50); self-loops are added
at batch time (dbize_graphs.py:25).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from multiprocessing import Pool
from typing import Callable, Iterable, Mapping, Sequence

import numpy as np

from deepdfa_tpu.frontend import (
    absdf,
    parser as cparser,
)
from deepdfa_tpu.frontend.cpg import CFG, Cpg
from deepdfa_tpu.frontend.vocab import AbsDfVocab, Fields, build_vocabs
from deepdfa_tpu.graphs.batch import GraphSpec
from deepdfa_tpu.nn.embedding import SUBKEY_ORDER


@dataclasses.dataclass
class ExtractedGraph:
    """Host-side intermediate: one function's model graph + features."""

    graph_id: int
    node_lines: np.ndarray  # [n] int32 source line per node
    edge_src: np.ndarray  # [e] int32 (CFG, no self loops)
    edge_dst: np.ndarray
    def_fields: dict[int, Fields]  # dense node idx -> stage-1 fields
    label: float  # function-level label
    #: optional reaching-definitions bit labels ([n, max_defs] float32 each:
    #: gen/kill/in/out) for the dataflow_solution_{in,out} label styles
    bits: dict[str, np.ndarray] | None = None
    #: per-edge relation ids (gtype="cfg+dep": 0=cfg, 1=data-dependence,
    #: 2=control-dependence); None for single-type cfg graphs
    edge_type: np.ndarray | None = None
    #: optional [n, NUM_STRUCT_FEATS] family-invariant structural channels
    #: (frontend/structfeat.py) appended to node_feats by to_graph_spec
    struct: np.ndarray | None = None

    @property
    def num_nodes(self) -> int:
        return int(self.node_lines.shape[0])


def extract_graph(
    code: str,
    graph_id: int,
    vuln_lines: set[int] | None = None,
    label: float | None = None,
    max_defs: int | None = None,
    gtype: str = "cfg",
    struct_feats: bool = False,
) -> ExtractedGraph | None:
    """Parse one function and build its model graph. None on failure or
    empty CFG (reference behavior: failures are skipped and logged,
    getgraphs.py:57-59).

    gtype selects the edge relations (the reference's gtype/rdg experiment
    axis, DDFA/sastvd/helpers/joern.py:419-441):
    - "cfg" (flagship): control-flow edges, single relation
    - "pdg": program-dependence graph — data + control dependences merged
      into ONE relation (the reference's rdg("pdg") reduction)
    - "cfg+dep": cfg (type 0) + data-dependence (1) + control-dependence
      (2) as typed edges for an n_etypes=3 GGNN
    """
    from deepdfa_tpu.core.config import GTYPE_ETYPES

    # validate BEFORE parsing: a bad gtype must fail fast on the first
    # call, not only on the subset of a corpus that happens to parse
    if gtype not in GTYPE_ETYPES:
        raise ValueError(f"gtype={gtype!r}")
    try:
        cpg = cparser.parse_function(code)
    except ValueError:
        return None
    return graph_from_cpg(
        cpg, graph_id, vuln_lines, label=label, max_defs=max_defs,
        gtype=gtype, struct_feats=struct_feats,
    )


def graph_from_cpg(
    cpg: Cpg,
    graph_id: int,
    vuln_lines: set[int] | None = None,
    label: float | None = None,
    max_defs: int | None = None,
    gtype: str = "cfg",
    struct_feats: bool = False,
) -> ExtractedGraph | None:
    """Model graph + features from an already-built CPG.

    The parser-independent half of `extract_graph`: the built-in parser
    and the Joern-backed serving frontend (serve/frontend.py, via
    frontend/joern_io.py:load_joern_cpg) both land here, so their
    features are computed by the same code."""
    from deepdfa_tpu.core.config import GTYPE_ETYPES

    if gtype not in GTYPE_ETYPES:
        raise ValueError(f"gtype={gtype!r}")

    keep = [
        nid
        for nid in cpg.cfg_nodes()
        if cpg.nodes[nid].line is not None
    ]
    if not keep:
        return None
    dense = {nid: i for i, nid in enumerate(keep)}
    keep_set = set(keep)

    node_lines = np.array([cpg.nodes[nid].line for nid in keep], np.int32)
    src, dst, typ = [], [], []
    if gtype != "pdg":
        for s, d, t in cpg.edges:
            if t == CFG and s in keep_set and d in keep_set:
                src.append(dense[s])
                dst.append(dense[d])
                typ.append(0)
    edge_type = None
    if gtype in ("pdg", "cfg+dep"):
        from deepdfa_tpu.frontend import deps as deps_mod

        # pdg merges both dependence kinds into one relation; cfg+dep
        # keeps them typed alongside cfg
        for tid, pairs in (
            (1, deps_mod.data_dependences(cpg)),
            (2, deps_mod.control_dependences(cpg)),
        ):
            for s, d in sorted(pairs):
                if s in keep_set and d in keep_set:
                    src.append(dense[s])
                    dst.append(dense[d])
                    typ.append(tid if gtype == "cfg+dep" else 0)
        if gtype == "cfg+dep":
            edge_type = np.array(typ, np.int32)
    def_fields: dict[int, Fields] = {}
    for nid in keep:
        if absdf.is_decl(cpg, nid):
            fields = absdf.decl_features(cpg, nid)
            if fields:
                def_fields[dense[nid]] = fields

    bits = None
    if max_defs is not None:
        # reaching-definitions supervision over the FULL CFG, remapped onto
        # the kept (line-bearing) nodes; graphs with zero definition sites
        # get all-zero arrays so the corpus stays fixed-width
        from deepdfa_tpu.nn.bitprop import rd_bit_problem

        prob = rd_bit_problem(cpg, max_defs, clip=True)
        n_keep = len(keep)
        bits = {
            k: np.zeros((n_keep, max_defs), np.float32)
            for k in ("gen", "kill", "labels_in", "labels_out")
        }
        if prob is not None:
            full_dense = {nid: i for i, nid in enumerate(prob["nodes"])}
            rows = np.array(
                [full_dense.get(nid, -1) for nid in keep], np.int64
            )
            ok = rows >= 0
            for k in bits:
                bits[k][ok] = prob[k][rows[ok]]

    if label is None:
        label = (
            1.0
            if vuln_lines and any(int(l) in vuln_lines for l in node_lines)
            else 0.0
        )
    struct = None
    if struct_feats:
        from deepdfa_tpu.frontend.structfeat import struct_features

        struct = struct_features(cpg, keep)
    return ExtractedGraph(
        graph_id=graph_id,
        node_lines=node_lines,
        edge_src=np.array(src, np.int32),
        edge_dst=np.array(dst, np.int32),
        def_fields=def_fields,
        label=float(label),
        bits=bits,
        edge_type=edge_type,
        struct=struct,
    )


def to_graph_spec(
    eg: ExtractedGraph,
    vocabs: Mapping[str, AbsDfVocab],
    vuln_lines: set[int] | None = None,
) -> GraphSpec:
    """Encode features through the vocab and emit the batchable GraphSpec."""
    from deepdfa_tpu.frontend.vocab import encode_nodes

    n = eg.num_nodes
    feats = encode_nodes(vocabs, eg.def_fields, range(n), SUBKEY_ORDER)
    if eg.struct is not None:
        # struct channels ride as extra columns; the embedding splits
        # them back out by position (nn/embedding.py struct_vocab)
        feats = np.concatenate([feats, eg.struct], axis=1)
    if vuln_lines:
        vuln = np.array(
            [1 if int(l) in vuln_lines else 0 for l in eg.node_lines], np.int32
        )
    else:
        vuln = np.zeros((n,), np.int32)
        if eg.label > 0:
            vuln[:] = 0  # graph label carried separately
    bit_kw = {}
    if eg.bits is not None:
        bit_kw = dict(
            node_gen=eg.bits["gen"],
            node_kill=eg.bits["kill"],
            node_bits_in=eg.bits["labels_in"],
            node_bits_out=eg.bits["labels_out"],
        )
    return GraphSpec(
        graph_id=eg.graph_id,
        node_feats=feats,
        node_vuln=vuln,
        edge_src=eg.edge_src,
        edge_dst=eg.edge_dst,
        label=eg.label,
        edge_type=eg.edge_type,
        **bit_kw,
    )


@dataclasses.dataclass
class Example:
    """One dataset row (reference schema: id, code, vul label, changed lines)."""

    id: int
    code: str
    label: float | None = None
    vuln_lines: frozenset[int] = frozenset()


def _extract_one(
    ex: Example, max_defs: int | None = None, gtype: str = "cfg",
    struct_feats: bool = False,
) -> ExtractedGraph | None:
    try:
        return extract_graph(
            ex.code, ex.id, set(ex.vuln_lines) or None, label=ex.label,
            max_defs=max_defs, gtype=gtype, struct_feats=struct_feats,
        )
    except Exception:
        # corpus-scale resilience: one pathological function must never
        # kill a 188k-example run (the reference skips and logs failures,
        # getgraphs.py:57-59); extract_graph handles parse errors itself,
        # this guards against anything unexpected deeper in the pipeline
        import logging
        import traceback

        logging.getLogger(__name__).warning(
            "extraction failed for example %s:\n%s", ex.id, traceback.format_exc()
        )
        return None


def extract_corpus(
    examples: Sequence[Example], workers: int = 0,
    max_defs: int | None = None, gtype: str = "cfg",
    struct_feats: bool = False,
) -> list[ExtractedGraph]:
    """Stage getgraphs+absdf-stage-1 over a corpus (mp fan-out like the
    reference's dfmp, sastvd/__init__.py:198-244)."""
    fn = partial(_extract_one, max_defs=max_defs, gtype=gtype,
                 struct_feats=struct_feats)
    if workers and workers > 1:
        with Pool(workers) as pool:
            out = pool.map(fn, examples, chunksize=64)
    else:
        out = [fn(ex) for ex in examples]
    return [g for g in out if g is not None]


def build_corpus_vocabs(
    examples: Sequence[Example],
    train_ids: Iterable[int],
    limit_all: int | None = 1000,
    limit_subkeys: int | None = 1000,
    workers: int = 0,
) -> dict[str, AbsDfVocab]:
    """Stage 1+2 over the TRAIN split only -> the shared vocabularies.

    This is the reference's abstract_dataflow stage ordering: the vocab is
    a corpus-level artifact built once before per-graph encoding, so
    sharded extraction jobs all encode against identical vocabularies."""
    train = set(train_ids)
    train_examples = [ex for ex in examples if ex.id in train]
    graphs = extract_corpus(train_examples, workers=workers)
    train_fields = [f for g in graphs for f in g.def_fields.values()]
    return build_vocabs(
        train_fields, SUBKEY_ORDER, limit_all=limit_all, limit_subkeys=limit_subkeys
    )


def encode_corpus(
    examples: Sequence[Example],
    vocabs: Mapping[str, AbsDfVocab],
    workers: int = 0,
    max_defs: int | None = None,
    gtype: str = "cfg",
    struct_feats: bool = False,
) -> list[GraphSpec]:
    """Extract + encode a corpus slice against pre-built vocabularies."""
    graphs = extract_corpus(
        examples, workers=workers, max_defs=max_defs, gtype=gtype,
        struct_feats=struct_feats,
    )
    by_id = {ex.id: ex for ex in examples}
    return [
        to_graph_spec(g, vocabs, set(by_id[g.graph_id].vuln_lines) or None)
        for g in graphs
    ]


def build_dataset(
    examples: Sequence[Example],
    train_ids: Iterable[int],
    limit_all: int | None = 1000,
    limit_subkeys: int | None = 1000,
    workers: int = 0,
    max_defs: int | None = None,
    gtype: str = "cfg",
    struct_feats: bool = False,
) -> tuple[list[GraphSpec], dict[str, AbsDfVocab]]:
    """Full single-process pipeline: extract, build train-split vocabs,
    encode everything. `max_defs` attaches reaching-definitions bit labels
    of that width for the dataflow_solution_{in,out} label styles;
    `gtype` selects the edge-relation set (see extract_graph)."""
    graphs = extract_corpus(
        examples, workers=workers, max_defs=max_defs, gtype=gtype,
        struct_feats=struct_feats,
    )
    train = set(train_ids)
    train_fields = [
        f
        for g in graphs
        if g.graph_id in train
        for f in g.def_fields.values()
    ]
    vocabs = build_vocabs(
        train_fields, SUBKEY_ORDER, limit_all=limit_all, limit_subkeys=limit_subkeys
    )
    by_id = {ex.id: ex for ex in examples}
    specs = [
        to_graph_spec(g, vocabs, set(by_id[g.graph_id].vuln_lines) or None)
        for g in graphs
    ]
    return specs, vocabs
