"""Synthetic Big-Vul-style corpus generator.

The real Big-Vul/MSR CSV (188k C/C++ functions, ~45GB with artifacts) is an
external download; this generator produces structurally similar
(function, fixed-function, changed-lines, label) rows so every pipeline
stage — parsing, CFG, reaching defs, abstract-dataflow vocab, batching,
training — runs hermetically at any scale. Vulnerable variants inject the
classic C bug families the datasets are built around (unbounded string
copy, missing bounds/null checks, off-by-one, integer-size truncation);
the "fix" is the patched form, so diff labels mark the buggy lines exactly
like the reference's git-diff labeling.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from deepdfa_tpu.data.diffs import vulnerable_lines
from deepdfa_tpu.data.pipeline import Example

_TYPES = ["int", "unsigned int", "size_t", "long", "char", "short"]
_APIS = ["malloc", "free", "memcpy", "memset", "strlen", "strcpy", "strncpy",
         "snprintf", "read", "write", "calloc", "realloc"]


@dataclasses.dataclass
class SynthExample:
    id: int
    before: str
    after: str
    label: int
    vuln_lines: frozenset[int]
    #: corpus-v2 provenance: bug-family name ("" = plain filler negative,
    #: "lookalike:<fam>" = benign twin), and whether the label was flipped
    #: by injected label noise
    family: str = ""
    noisy: bool = False


def _body_lines(rng: np.random.Generator, n_stmts: int, vulnerable: bool):
    """Returns (before_lines, after_lines). Lines are function-body lines."""
    before: list[str] = []
    after: list[str] = []

    def both(s):
        before.append(s)
        after.append(s)

    both("    char buf[64];")
    both("    int i = 0;")
    both("    int total = 0;")
    both(f"    {_TYPES[int(rng.integers(0, len(_TYPES)))]} tmp = 0;")

    # Every bug family plants at least one *definition* statement with a
    # distinctive abstract-dataflow feature combination (api/datatype/
    # literal/operator) — DeepDFA's features only live on definition nodes,
    # which is exactly how the real datasets' vulnerable functions are
    # recognized (paper §4.1).
    bug = int(rng.integers(0, 4)) if vulnerable else -1
    if bug == 0:
        # unbounded copy: length taken but never clamped
        before.append("    total = strlen(src) + len;")
        before.append("    strcpy(buf, src);")
        after.append("    total = strlen(src);")
        after.append("    strncpy(buf, src, sizeof(buf) - 1);")
        after.append("    buf[sizeof(buf) - 1] = 0;")
    elif bug == 1:
        # missing bounds check on memcpy with sizeof-scaled length
        before.append("    tmp = len * sizeof(char);")
        before.append("    memcpy(buf, src, len);")
        after.append("    if (len > (int)sizeof(buf)) {")
        after.append("        len = (int)sizeof(buf);")
        after.append("    }")
        after.append("    memcpy(buf, src, len);")
    elif bug == 2:
        # off-by-one: index runs to len + 1
        before.append("    i = len + 1;")
        before.append("    total += src[i];")
        after.append("    i = len - 1;")
        after.append("    if (i >= 0) {")
        after.append("        total += src[i];")
        after.append("    }")
    elif bug == 3:
        # unchecked malloc deref
        before.append("    char *p = malloc(len);")
        before.append("    p[0] = 1;")
        after.append("    char *p = malloc(len);")
        after.append("    if (!p) {")
        after.append("        return -1;")
        after.append("    }")
        after.append("    p[0] = 1;")
        both("    free(p);")
    # benign filler statements
    for _ in range(n_stmts):
        k = int(rng.integers(0, 6))
        if k == 0:
            both(f"    tmp = tmp + {int(rng.integers(1, 100))};")
        elif k == 1:
            both(f"    total += i * {int(rng.integers(2, 9))};")
        elif k == 2:
            both("    if (total > tmp) {")
            both(f"        tmp = total - {int(rng.integers(1, 10))};")
            both("    }")
        elif k == 3:
            both(f"    while (i < {int(rng.integers(4, 32))}) {{")
            both("        i++;")
            both("    }")
        elif k == 4:
            api = _APIS[int(rng.integers(0, len(_APIS)))]
            both(f"    total ^= (int){api}(buf);" if api == "strlen"
                 else f"    memset(buf, 0, sizeof(buf));")
        else:
            both(f"    tmp ^= total >> {int(rng.integers(1, 5))};")
    both("    return total;")
    return before, after


def bigvul_stmt_sizes(
    n: int, seed: int = 0, median: float = 14.0, sigma: float = 1.2,
    max_stmts: int = 500,
) -> np.ndarray:
    """Big-Vul-like heavy-tail statement counts (lognormal, clipped).

    Real Big-Vul functions have a median of ~15 lines with a long tail into
    the hundreds — heavy enough that the reference drops its test batch size
    to 16 to fit the tail on GPU (DDFA/sastvd/linevd/datamodule.py:135-141).
    A lognormal with median 14 and sigma 1.2 reproduces that shape (p99 ≈
    230 statements, clipped at 500); benchmarks packed from these sizes are
    comparable to the reference's per-example timings in a way uniform
    2-12-statement toys are not.
    """
    rng = np.random.default_rng(seed)
    sizes = rng.lognormal(mean=float(np.log(median)), sigma=sigma, size=n)
    return np.clip(sizes.astype(np.int64), 2, max_stmts)


def generate(
    n: int,
    vuln_rate: float = 0.06,
    seed: int = 0,
    min_stmts: int = 2,
    max_stmts: int = 12,
    stmt_sizes: np.ndarray | None = None,
) -> list[SynthExample]:
    """Generate `n` examples with the dataset's ~6% positive rate.

    `stmt_sizes` (e.g. from `bigvul_stmt_sizes`) overrides the uniform
    [min_stmts, max_stmts] statement-count draw per example.
    """
    if stmt_sizes is not None and len(stmt_sizes) < n:
        raise ValueError(f"stmt_sizes has {len(stmt_sizes)} entries, need {n}")
    rng = np.random.default_rng(seed)
    out: list[SynthExample] = []
    for gid in range(n):
        vulnerable = bool(rng.random() < vuln_rate)
        if stmt_sizes is not None:
            n_stmts = int(stmt_sizes[gid])
        else:
            n_stmts = int(rng.integers(min_stmts, max_stmts + 1))
        bl, al = _body_lines(rng, n_stmts, vulnerable)
        fname = f"fn_{gid}"
        sig = f"int {fname}(char *src, int len)"
        before = sig + " {\n" + "\n".join(bl) + "\n}\n"
        after = sig + " {\n" + "\n".join(al) + "\n}\n"
        lines = frozenset(vulnerable_lines(before, after)) if vulnerable else frozenset()
        out.append(
            SynthExample(
                id=gid,
                before=before,
                after=after,
                label=int(vulnerable),
                vuln_lines=lines,
            )
        )
    return out


# ---------------------------------------------------------------------------
# corpus v2 (VERDICT r3 item 4): a synthetic task that CANNOT be solved by
# counting tokens/features.
#
# The round-3 corpus was suspiciously easy (test precision 1.000): every
# bug family's buggy form contained feature buckets its fixed form lacked,
# so a bag-of-subkeys classifier separates it linearly. v2 closes that in
# three ways:
#   - ORDER families: the vulnerable and fixed forms contain the SAME
#     statement multiset — only the order differs (guard dominates the use
#     in the fixed form; follows it in the buggy one). Identical subkey
#     histograms, distinguishable only through control/data flow — the
#     dynamics of paper Table 3 (DeepDFA wins via dataflow, not tokens).
#   - BENIGN LOOKALIKES: a configurable share of negatives embed the FIXED
#     form of a random family, so "contains memcpy/clamp/null-check tokens"
#     stops predicting the label for the additive families too.
#   - LABEL NOISE + randomized family placement among filler, killing
#     position heuristics and perfect separability.
# The trivial-baseline control lives in eval/trivial_baseline.py; the
# committed evidence is docs/convergence_run.json (scripts/train_flagship.py
# --corpus v2) where the GGNN must beat that control by a clear margin.

_CLAMP_GUARD = [
    "    if (len > (int)sizeof(buf)) {",
    "        len = (int)sizeof(buf);",
    "    }",
]


def _fam_clamp_order(v: bool) -> list[str]:
    use = ["    memcpy(buf, src, len);"]
    return use + _CLAMP_GUARD if v else _CLAMP_GUARD + use


def _fam_null_check_order(v: bool) -> list[str]:
    alloc = ["    char *p = malloc(len + 1);"]
    guard = ["    if (!p) {", "        return -1;", "    }"]
    use = ["    p[0] = 1;"]
    tail = ["    free(p);"]
    return alloc + (use + guard if v else guard + use) + tail


def _fam_use_after_free(v: bool) -> list[str]:
    alloc = ["    char *q = malloc(16);", "    if (!q) {",
             "        return -1;", "    }", "    q[0] = 2;"]
    use = ["    total += q[0];"]
    fr = ["    free(q);"]
    return alloc + (fr + use if v else use + fr)


def _fam_index_clamp_order(v: bool) -> list[str]:
    setl = ["    i = len;"]
    guard = ["    if (i >= (int)sizeof(buf)) {",
             "        i = (int)sizeof(buf) - 1;", "    }"]
    use = ["    total += buf[i];"]
    return setl + (use + guard if v else guard + use)


def _fam_unbounded_copy(v: bool) -> list[str]:
    if v:
        return ["    total = strlen(src) + len;", "    strcpy(buf, src);"]
    return ["    total = strlen(src);",
            "    strncpy(buf, src, sizeof(buf) - 1);",
            "    buf[sizeof(buf) - 1] = 0;"]


def _fam_missing_bounds(v: bool) -> list[str]:
    if v:
        return ["    tmp = len * sizeof(char);", "    memcpy(buf, src, len);"]
    return _CLAMP_GUARD + ["    memcpy(buf, src, len);"]


def _fam_off_by_one(v: bool) -> list[str]:
    if v:
        return ["    i = len + 1;", "    total += src[i];"]
    return ["    i = len - 1;", "    if (i >= 0) {",
            "        total += src[i];", "    }"]


def _fam_truncation(v: bool) -> list[str]:
    # integer-size truncation before an allocation-sized write
    if v:
        return ["    short n = (short)(len * 2);",
                "    char *w = malloc(n);",
                "    if (!w) {", "        return -1;", "    }",
                "    memset(w, 0, len * 2);", "    free(w);"]
    return ["    long n = (long)len * 2;",
            "    char *w = malloc(n);",
            "    if (!w) {", "        return -1;", "    }",
            "    memset(w, 0, n);", "    free(w);"]


#: order-sensitive families share the exact statement multiset between the
#: two forms; additive families differ in content but their fixed forms
#: also appear as benign lookalikes
V2_FAMILIES: dict[str, object] = {
    "clamp_order": _fam_clamp_order,
    "null_check_order": _fam_null_check_order,
    "use_after_free": _fam_use_after_free,
    "index_clamp_order": _fam_index_clamp_order,
    "unbounded_copy": _fam_unbounded_copy,
    "missing_bounds": _fam_missing_bounds,
    "off_by_one": _fam_off_by_one,
    "truncation": _fam_truncation,
}

#: safe API usages sprinkled into ANY example so raw API presence
#: (strcpy/memcpy/malloc/free) carries no label signal
_SAFE_FILLER = [
    ['    strcpy(buf, "ok");'],
    ["    memcpy(buf, src, sizeof(buf));"],
    ["    char *r = malloc(8);", "    if (r) {", "        r[0] = 1;",
     "        free(r);", "    }"],
    ["    total ^= (int)strlen(buf);"],
]


def _v2_filler_block(rng: np.random.Generator) -> list[str]:
    k = int(rng.integers(0, 8))
    if k == 0:
        return [f"    tmp = tmp + {int(rng.integers(1, 100))};"]
    if k == 1:
        return [f"    total += i * {int(rng.integers(2, 9))};"]
    if k == 2:
        return ["    if (total > tmp) {",
                f"        tmp = total - {int(rng.integers(1, 10))};", "    }"]
    if k == 3:
        return [f"    while (i < {int(rng.integers(4, 32))}) {{",
                "        i++;", "    }"]
    if k == 4:
        return [f"    tmp ^= total >> {int(rng.integers(1, 5))};"]
    if k == 5:
        return ["    memset(buf, 0, sizeof(buf));"]
    return list(_SAFE_FILLER[int(rng.integers(0, len(_SAFE_FILLER)))])


def generate_v2(
    n: int,
    vuln_rate: float = 0.06,
    seed: int = 0,
    min_stmts: int = 2,
    max_stmts: int = 12,
    stmt_sizes: np.ndarray | None = None,
    lookalike_rate: float = 0.5,
    label_noise: float = 0.0,
    families: list[str] | None = None,
) -> list[SynthExample]:
    """Corpus v2: order families + benign lookalikes + label noise.

    `families` restricts the bug families drawn (default all); the
    holdout-family generalization split is built by the caller from the
    per-example `family` field."""
    if stmt_sizes is not None and len(stmt_sizes) < n:
        raise ValueError(f"stmt_sizes has {len(stmt_sizes)} entries, need {n}")
    fam_names = list(families or V2_FAMILIES)
    rng = np.random.default_rng(seed)
    noise_rng = np.random.default_rng(seed + 101)
    out: list[SynthExample] = []
    for gid in range(n):
        vulnerable = bool(rng.random() < vuln_rate)
        if stmt_sizes is not None:
            n_stmts = int(stmt_sizes[gid])
        else:
            n_stmts = int(rng.integers(min_stmts, max_stmts + 1))

        decls = [
            "    char buf[64];",
            "    int i = 0;",
            "    int total = 0;",
            f"    {_TYPES[int(rng.integers(0, len(_TYPES)))]} tmp = 0;",
        ]
        blocks = [_v2_filler_block(rng) for _ in range(n_stmts)]
        family = ""
        fam_before: list[str] | None = None
        fam_after: list[str] | None = None
        if vulnerable:
            family = fam_names[int(rng.integers(0, len(fam_names)))]
            fam_fn = V2_FAMILIES[family]
            fam_before, fam_after = fam_fn(True), fam_fn(False)
        elif rng.random() < lookalike_rate:
            # benign twin: the FIXED form of a random family, unchanged
            fam = fam_names[int(rng.integers(0, len(fam_names)))]
            family = f"lookalike:{fam}"
            fam_before = fam_after = V2_FAMILIES[fam](False)
        pos = int(rng.integers(0, len(blocks) + 1))
        if fam_before is not None:
            blocks_before = blocks[:pos] + [fam_before] + blocks[pos:]
            blocks_after = blocks[:pos] + [fam_after] + blocks[pos:]
        else:
            blocks_before = blocks_after = blocks

        def _assemble(bls):
            body = [line for b in bls for line in b]
            sig = f"int fn_{gid}(char *src, int len)"
            return sig + " {\n" + "\n".join(decls + body) + "\n    return total;\n}\n"

        before = _assemble(blocks_before)
        after = _assemble(blocks_after)
        label = int(vulnerable)
        lines = (
            frozenset(vulnerable_lines(before, after)) if vulnerable else frozenset()
        )
        noisy = bool(label_noise and noise_rng.random() < label_noise)
        if noisy:
            label = 1 - label
            if label == 0:
                lines = frozenset()  # a "benign" label carries no line labels
        out.append(
            SynthExample(
                id=gid, before=before, after=after, label=label,
                vuln_lines=lines, family=family, noisy=noisy,
            )
        )
    return out


def to_examples(synth: list[SynthExample]) -> list[Example]:
    return [
        Example(
            id=s.id, code=s.before, label=float(s.label), vuln_lines=s.vuln_lines
        )
        for s in synth
    ]


def split_ids(
    n: int, seed: int = 0, train: float = 0.8, val: float = 0.1
) -> tuple[list[int], list[int], list[int]]:
    """Random disjoint train/val/test id splits (reference keeps fixed
    splits in csv; synthetic data splits by seeded permutation)."""
    rng = np.random.default_rng(seed)
    perm = rng.permutation(n)
    n_train = int(n * train)
    n_val = int(n * val)
    return (
        perm[:n_train].tolist(),
        perm[n_train : n_train + n_val].tolist(),
        perm[n_train + n_val :].tolist(),
    )


def flagship_corpus(
    n_examples: int,
    seed: int = 7,
    vuln_rate: float = 0.06,
    limit_all: int = 1000,
    workers: int = 0,
):
    """GraphSpecs for the flagship benchmark workload: Big-Vul-tail CFG
    sizes through the FULL frontend pipeline at the flagship feature
    limits (limit_all 1000 -> input_dim 1002). The single definition
    shared by bench.py, scripts/bench_prefetch.py, and anything else
    that claims to measure "the flagship workload" — so the corpus can
    never silently diverge between benchmarks."""
    from deepdfa_tpu.data.pipeline import build_dataset

    sizes = bigvul_stmt_sizes(n_examples, seed=seed)
    synth = generate(
        n_examples, vuln_rate=vuln_rate, seed=seed, stmt_sizes=sizes
    )
    specs, _ = build_dataset(
        to_examples(synth), train_ids=range(n_examples),
        limit_all=limit_all, limit_subkeys=limit_all, workers=workers,
    )
    return specs
