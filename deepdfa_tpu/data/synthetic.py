"""Synthetic Big-Vul-style corpus generator.

The real Big-Vul/MSR CSV (188k C/C++ functions, ~45GB with artifacts) is an
external download; this generator produces structurally similar
(function, fixed-function, changed-lines, label) rows so every pipeline
stage — parsing, CFG, reaching defs, abstract-dataflow vocab, batching,
training — runs hermetically at any scale. Vulnerable variants inject the
classic C bug families the datasets are built around (unbounded string
copy, missing bounds/null checks, off-by-one, integer-size truncation);
the "fix" is the patched form, so diff labels mark the buggy lines exactly
like the reference's git-diff labeling.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from deepdfa_tpu.data.diffs import vulnerable_lines
from deepdfa_tpu.data.pipeline import Example

_TYPES = ["int", "unsigned int", "size_t", "long", "char", "short"]
_APIS = ["malloc", "free", "memcpy", "memset", "strlen", "strcpy", "strncpy",
         "snprintf", "read", "write", "calloc", "realloc"]


@dataclasses.dataclass
class SynthExample:
    id: int
    before: str
    after: str
    label: int
    vuln_lines: frozenset[int]


def _body_lines(rng: np.random.Generator, n_stmts: int, vulnerable: bool):
    """Returns (before_lines, after_lines). Lines are function-body lines."""
    before: list[str] = []
    after: list[str] = []

    def both(s):
        before.append(s)
        after.append(s)

    both("    char buf[64];")
    both("    int i = 0;")
    both("    int total = 0;")
    both(f"    {_TYPES[int(rng.integers(0, len(_TYPES)))]} tmp = 0;")

    # Every bug family plants at least one *definition* statement with a
    # distinctive abstract-dataflow feature combination (api/datatype/
    # literal/operator) — DeepDFA's features only live on definition nodes,
    # which is exactly how the real datasets' vulnerable functions are
    # recognized (paper §4.1).
    bug = int(rng.integers(0, 4)) if vulnerable else -1
    if bug == 0:
        # unbounded copy: length taken but never clamped
        before.append("    total = strlen(src) + len;")
        before.append("    strcpy(buf, src);")
        after.append("    total = strlen(src);")
        after.append("    strncpy(buf, src, sizeof(buf) - 1);")
        after.append("    buf[sizeof(buf) - 1] = 0;")
    elif bug == 1:
        # missing bounds check on memcpy with sizeof-scaled length
        before.append("    tmp = len * sizeof(char);")
        before.append("    memcpy(buf, src, len);")
        after.append("    if (len > (int)sizeof(buf)) {")
        after.append("        len = (int)sizeof(buf);")
        after.append("    }")
        after.append("    memcpy(buf, src, len);")
    elif bug == 2:
        # off-by-one: index runs to len + 1
        before.append("    i = len + 1;")
        before.append("    total += src[i];")
        after.append("    i = len - 1;")
        after.append("    if (i >= 0) {")
        after.append("        total += src[i];")
        after.append("    }")
    elif bug == 3:
        # unchecked malloc deref
        before.append("    char *p = malloc(len);")
        before.append("    p[0] = 1;")
        after.append("    char *p = malloc(len);")
        after.append("    if (!p) {")
        after.append("        return -1;")
        after.append("    }")
        after.append("    p[0] = 1;")
        both("    free(p);")
    # benign filler statements
    for _ in range(n_stmts):
        k = int(rng.integers(0, 6))
        if k == 0:
            both(f"    tmp = tmp + {int(rng.integers(1, 100))};")
        elif k == 1:
            both(f"    total += i * {int(rng.integers(2, 9))};")
        elif k == 2:
            both("    if (total > tmp) {")
            both(f"        tmp = total - {int(rng.integers(1, 10))};")
            both("    }")
        elif k == 3:
            both(f"    while (i < {int(rng.integers(4, 32))}) {{")
            both("        i++;")
            both("    }")
        elif k == 4:
            api = _APIS[int(rng.integers(0, len(_APIS)))]
            both(f"    total ^= (int){api}(buf);" if api == "strlen"
                 else f"    memset(buf, 0, sizeof(buf));")
        else:
            both(f"    tmp ^= total >> {int(rng.integers(1, 5))};")
    both("    return total;")
    return before, after


def bigvul_stmt_sizes(
    n: int, seed: int = 0, median: float = 14.0, sigma: float = 1.2,
    max_stmts: int = 500,
) -> np.ndarray:
    """Big-Vul-like heavy-tail statement counts (lognormal, clipped).

    Real Big-Vul functions have a median of ~15 lines with a long tail into
    the hundreds — heavy enough that the reference drops its test batch size
    to 16 to fit the tail on GPU (DDFA/sastvd/linevd/datamodule.py:135-141).
    A lognormal with median 14 and sigma 1.2 reproduces that shape (p99 ≈
    230 statements, clipped at 500); benchmarks packed from these sizes are
    comparable to the reference's per-example timings in a way uniform
    2-12-statement toys are not.
    """
    rng = np.random.default_rng(seed)
    sizes = rng.lognormal(mean=float(np.log(median)), sigma=sigma, size=n)
    return np.clip(sizes.astype(np.int64), 2, max_stmts)


def generate(
    n: int,
    vuln_rate: float = 0.06,
    seed: int = 0,
    min_stmts: int = 2,
    max_stmts: int = 12,
    stmt_sizes: np.ndarray | None = None,
) -> list[SynthExample]:
    """Generate `n` examples with the dataset's ~6% positive rate.

    `stmt_sizes` (e.g. from `bigvul_stmt_sizes`) overrides the uniform
    [min_stmts, max_stmts] statement-count draw per example.
    """
    if stmt_sizes is not None and len(stmt_sizes) < n:
        raise ValueError(f"stmt_sizes has {len(stmt_sizes)} entries, need {n}")
    rng = np.random.default_rng(seed)
    out: list[SynthExample] = []
    for gid in range(n):
        vulnerable = bool(rng.random() < vuln_rate)
        if stmt_sizes is not None:
            n_stmts = int(stmt_sizes[gid])
        else:
            n_stmts = int(rng.integers(min_stmts, max_stmts + 1))
        bl, al = _body_lines(rng, n_stmts, vulnerable)
        fname = f"fn_{gid}"
        sig = f"int {fname}(char *src, int len)"
        before = sig + " {\n" + "\n".join(bl) + "\n}\n"
        after = sig + " {\n" + "\n".join(al) + "\n}\n"
        lines = frozenset(vulnerable_lines(before, after)) if vulnerable else frozenset()
        out.append(
            SynthExample(
                id=gid,
                before=before,
                after=after,
                label=int(vulnerable),
                vuln_lines=lines,
            )
        )
    return out


def to_examples(synth: list[SynthExample]) -> list[Example]:
    return [
        Example(
            id=s.id, code=s.before, label=float(s.label), vuln_lines=s.vuln_lines
        )
        for s in synth
    ]


def split_ids(
    n: int, seed: int = 0, train: float = 0.8, val: float = 0.1
) -> tuple[list[int], list[int], list[int]]:
    """Random disjoint train/val/test id splits (reference keeps fixed
    splits in csv; synthetic data splits by seeded permutation)."""
    rng = np.random.default_rng(seed)
    perm = rng.permutation(n)
    n_train = int(n * train)
    n_val = int(n * val)
    return (
        perm[:n_train].tolist(),
        perm[n_train : n_train + n_val].tolist(),
        perm[n_train + n_val :].tolist(),
    )


def flagship_corpus(
    n_examples: int,
    seed: int = 7,
    vuln_rate: float = 0.06,
    limit_all: int = 1000,
    workers: int = 0,
):
    """GraphSpecs for the flagship benchmark workload: Big-Vul-tail CFG
    sizes through the FULL frontend pipeline at the flagship feature
    limits (limit_all 1000 -> input_dim 1002). The single definition
    shared by bench.py, scripts/bench_prefetch.py, and anything else
    that claims to measure "the flagship workload" — so the corpus can
    never silently diverge between benchmarks."""
    from deepdfa_tpu.data.pipeline import build_dataset

    sizes = bigvul_stmt_sizes(n_examples, seed=seed)
    synth = generate(
        n_examples, vuln_rate=vuln_rate, seed=seed, stmt_sizes=sizes
    )
    specs, _ = build_dataset(
        to_examples(synth), train_ids=range(n_examples),
        limit_all=limit_all, limit_subkeys=limit_all, workers=workers,
    )
    return specs
