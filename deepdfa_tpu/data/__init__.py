from deepdfa_tpu.data.diffs import diff_lines, vulnerable_lines
from deepdfa_tpu.data.mp_pack import MpPacker, mp_shard_bucket_batches
from deepdfa_tpu.data.packed_cache import (
    PackedBatchCache,
    cache_key,
    corpus_digest,
)
from deepdfa_tpu.data.pipeline import (
    Example,
    ExtractedGraph,
    build_dataset,
    extract_corpus,
    extract_graph,
    graph_from_cpg,
    to_graph_spec,
)
from deepdfa_tpu.data.prefetch import PipelineStats, device_placer, prefetch
from deepdfa_tpu.data.synthetic import (
    SynthExample,
    bigvul_stmt_sizes,
    flagship_corpus,
    generate,
    split_ids,
    to_examples,
)

__all__ = [
    "diff_lines",
    "vulnerable_lines",
    "MpPacker",
    "mp_shard_bucket_batches",
    "PackedBatchCache",
    "cache_key",
    "corpus_digest",
    "PipelineStats",
    "device_placer",
    "prefetch",
    "Example",
    "ExtractedGraph",
    "build_dataset",
    "extract_corpus",
    "extract_graph",
    "graph_from_cpg",
    "to_graph_spec",
    "SynthExample",
    "bigvul_stmt_sizes",
    "flagship_corpus",
    "generate",
    "split_ids",
    "to_examples",
]
