"""Multiprocess packing producer: parallelize first-epoch batch packing
across cores.

Packing (graphs/batch.py:pack) is GIL-bound python/numpy slicing, so the
prefetch THREAD cannot scale it — this module distributes it over a spawn
process pool instead (the reference leans on DataLoader worker processes
for the same reason, DDFA/sastvd/linevd/datamodule.py:110-141). The split
mirrors the batcher's own structure: the parent runs the cheap sequential
PLANNER (`plan_shard_bucket_batches`), workers run `pack_plan` on the
numpy-heavy plans, and results come back through POSIX shared memory —
one writer-side copy into the segment and one reader-side copy out, never
a pickle of array bytes through a pipe. Order and content are

bit-identical to the inline batcher (same plans, same pack function;
pinned by tests/test_packed_cache.py).

Spawn safety: workers receive the corpus once at pool construction (not
per task), the worker entry points are module-level, and nothing here
requires fork semantics — safe next to an initialized TPU/XLA runtime,
which fork would corrupt.

Scope note: this accelerates the COLD path (first epoch of a new cache
key). Epochs >= 2 should replay the packed-batch cache
(data/packed_cache.py), which skips packing entirely.
"""

from __future__ import annotations

import dataclasses
import itertools
import multiprocessing as mp
import os
from collections import deque
from multiprocessing import shared_memory
from pathlib import Path
from typing import Iterable, Iterator, Sequence

import numpy as np

from deepdfa_tpu.core.config import PAD_ID_BY_FAMILY
from deepdfa_tpu.obs import trace as obs_trace
from deepdfa_tpu.data.text import (
    TEXT_ARRAY_FIELDS as _TEXT_FIELDS,
    TextBatch,
    TextBatchPlan,
    collate_plan,
    plan_bucketed_batches,
)
from deepdfa_tpu.graphs.batch import (
    ARRAY_FIELDS as _ARRAY_FIELDS,
    BatchPlan,
    GraphBatch,
    GraphSpec,
    pack_plan,
    plan_shard_bucket_batches,
)

# worker-process globals, set once by _init_worker (spawn ships them via
# the initargs pickle exactly once per worker, not per task)
_WORKER: dict = {}

#: segments are NAMED "<_SHM_PREFIX>-<parent pid>-<packer token>-..." so
#: the parent can sweep leftovers it never received: terminate() discards
#: queued results and kills mid-pack workers, and with track=False nothing
#: else ever unlinks those segments (close() sweeps its own prefix;
#: _sweep_stale collects dead parents' leftovers from crashed runs)
_SHM_PREFIX = "dfapack"
_SHM_DIR = Path("/dev/shm")
_PACKER_TOKENS = itertools.count()


def _init_worker(
    graphs: Sequence[GraphSpec],
    add_self_loops: bool,
    shm_prefix: str = "",
) -> None:
    _WORKER["graphs"] = graphs
    _WORKER["add_self_loops"] = add_self_loops
    _WORKER["shm_prefix"] = shm_prefix
    _WORKER["seq"] = 0


def _shm_create(size: int) -> shared_memory.SharedMemory:
    name = None
    if _WORKER.get("shm_prefix"):
        _WORKER["seq"] += 1
        name = f"{_WORKER['shm_prefix']}{os.getpid()}-{_WORKER['seq']}"
    try:
        # track=False (3.13+): the segment's lifetime is managed by the
        # PARENT (attach -> copy out -> unlink); without it the worker's
        # resource tracker would warn about / unlink segments it thinks
        # leaked
        return shared_memory.SharedMemory(
            name=name, create=True, size=size, track=False
        )
    except TypeError:
        shm = shared_memory.SharedMemory(name=name, create=True, size=size)
        try:
            from multiprocessing import resource_tracker

            resource_tracker.unregister(shm._name, "shared_memory")
        except Exception:
            pass
        return shm


def _sweep_prefix(prefix: str) -> int:
    """Unlink every segment under `prefix` (linux /dev/shm backing; a
    no-op elsewhere — non-linux callers only leak on terminate, which the
    pickle fallback already tolerates). Returns segments removed."""
    if not _SHM_DIR.is_dir():
        return 0
    n = 0
    for p in _SHM_DIR.glob(f"{prefix}*"):
        try:
            p.unlink()
            n += 1
        except OSError:
            pass
    return n


def _sweep_stale() -> int:
    """Collect segments left by packer parents that are GONE (hard crash
    / kill -9: no close(), no _drain). Own-pid and live-pid prefixes are
    never touched — a sibling packer in this or another live process may
    be mid-pack."""
    if not _SHM_DIR.is_dir():
        return 0
    n = 0
    for p in _SHM_DIR.glob(f"{_SHM_PREFIX}-*"):
        try:
            owner = int(p.name.split("-")[1])
        except (IndexError, ValueError):
            continue
        if owner == os.getpid():
            continue
        try:
            os.kill(owner, 0)
            continue  # owner alive
        except ProcessLookupError:
            pass  # owner gone -> segment is garbage
        except OSError:
            continue  # e.g. EPERM: alive, different user
        try:
            p.unlink()
            n += 1
        except OSError:
            pass
    return n


def _write_shm(leaves) -> tuple[str, list]:
    """Copy (name, array) leaves into one fresh segment; (shm name,
    manifest). Raises OSError when no segment can be created (e.g.
    /dev/shm exhausted) — callers fall back to pickling the batch."""
    total = sum(a.nbytes for _, a in leaves)
    shm = _shm_create(max(1, total))
    manifest = []
    off = 0
    for name, a in leaves:
        dst = np.ndarray(a.shape, dtype=a.dtype, buffer=shm.buf, offset=off)
        dst[...] = a
        manifest.append((name, str(a.dtype), a.shape, off))
        off += a.nbytes
    name = shm.name
    shm.close()
    return name, manifest


def _pack_one(plan: BatchPlan):
    """Worker entry: pack one plan, hand the arrays back via shared
    memory. Returns ("shm", name, manifest, num_graphs) or, when a
    segment cannot be created (e.g. /dev/shm exhausted),
    ("pickle", batch) as a degraded-but-correct fallback.

    Spans: workers inherit the parent's exported trace dir (spawn ships
    os.environ), so pack work lands in the merged timeline as
    cat="pack_worker" events from the worker's own pid; the flush per
    task matters because pool.terminate() would discard a buffer."""
    with obs_trace.span("pack_plan", cat="pack_worker"):
        batch = pack_plan(
            _WORKER["graphs"], plan, _WORKER["add_self_loops"]
        )
    obs_trace.flush()
    leaves = [
        (name, np.ascontiguousarray(getattr(batch, name)))
        for name in _ARRAY_FIELDS
        if getattr(batch, name) is not None
    ]
    try:
        name, manifest = _write_shm(leaves)
    except OSError:
        return ("pickle", batch)
    return ("shm", name, manifest, int(batch.num_graphs))


def _init_text_worker(
    token_ids_by_id,
    labels_by_id,
    graphs_by_id,
    pad_id: int,
    shm_prefix: str = "",
) -> None:
    _WORKER["token_ids"] = token_ids_by_id
    _WORKER["labels"] = labels_by_id
    _WORKER["graphs_by_id"] = graphs_by_id
    _WORKER["pad_id"] = pad_id
    _WORKER["shm_prefix"] = shm_prefix
    _WORKER["seq"] = 0


def _collate_text_one(plan: TextBatchPlan):
    """Worker entry for bucketed text plans: materialize `collate_plan`
    and ship the TextBatch — its own leaves plus "graphs."-prefixed
    nested GraphBatch leaves — through one segment."""
    with obs_trace.span("collate_plan", cat="pack_worker"):
        batch = collate_plan(
            plan,
            _WORKER["token_ids"],
            _WORKER["labels"],
            _WORKER["graphs_by_id"],
            _WORKER["pad_id"],
        )
    obs_trace.flush()
    leaves = [
        (name, np.ascontiguousarray(np.asarray(getattr(batch, name))))
        for name in _TEXT_FIELDS
    ]
    g = batch.graphs
    leaves += [
        (f"graphs.{name}", np.ascontiguousarray(np.asarray(v)))
        for name in _ARRAY_FIELDS
        if (v := getattr(g, name)) is not None
    ]
    try:
        name, manifest = _write_shm(leaves)
    except OSError:
        return ("pickle", batch)
    return ("shm", name, manifest, int(g.num_graphs))


def _discard_shm(name: str) -> None:
    """Unlink a segment whose contents will never be received (consumer
    abandoned the stream) — only the parent may unlink (track=False)."""
    try:
        shm = shared_memory.SharedMemory(name=name)
    except FileNotFoundError:
        return
    shm.close()
    try:
        shm.unlink()
    except FileNotFoundError:
        pass


def _read_shm_arrays(name: str, manifest) -> dict[str, np.ndarray]:
    """Copy every manifest leaf out of a segment, then unlink it —
    holding mmap views hostage to consumer lifetime risks BufferError on
    close and /dev/shm leaks on crash; the copy is one memcpy and the
    batch is device_put right after anyway (zero-copy host replay is the
    cache's job, data/packed_cache.py)."""
    shm = shared_memory.SharedMemory(name=name)
    try:
        return {
            fname: np.ndarray(
                tuple(shape), dtype=np.dtype(dtype), buffer=shm.buf,
                offset=off,
            ).copy()
            for fname, dtype, shape, off in manifest
        }
    finally:
        shm.close()
        try:
            shm.unlink()
        except FileNotFoundError:
            pass


def _receive(result) -> GraphBatch:
    if result[0] == "pickle":
        return result[1]
    _, name, manifest, num_graphs = result
    arrays = _read_shm_arrays(name, manifest)
    return GraphBatch(
        **{n: arrays.get(n) for n in _ARRAY_FIELDS},
        num_graphs=num_graphs,
    )


def _receive_text(result) -> TextBatch:
    if result[0] == "pickle":
        return result[1]
    _, name, manifest, num_graphs = result
    arrays = _read_shm_arrays(name, manifest)
    graphs = {
        k[len("graphs."):]: v
        for k, v in arrays.items()
        if k.startswith("graphs.")
    }
    return TextBatch(
        **{n: arrays.get(n) for n in _TEXT_FIELDS},
        graphs=GraphBatch(
            **{n: graphs.get(n) for n in _ARRAY_FIELDS},
            num_graphs=num_graphs,
        ),
    )


class _PoolPacker:
    """Shared spawn-pool mechanics for the batch packers.

    Construction cost (spawn + state pickle + jax import per worker) is
    paid once, lazily on the first `pack` that needs it — a caller can
    hold a packer for a whole run and never spawn a worker if every
    epoch replays the packed-batch cache. Use as a context manager, or
    call close(). Subclasses bind the worker entry points:
    `_init_fn`/`_init_args()` (pool initializer), `_task_fn` (one item
    -> shm/pickle result), `_receive_fn` (result -> batch) and
    `_pack_inline` (the workers<=1 fallback).
    """

    _init_fn = None
    _task_fn = None
    _receive_fn = None

    def __init__(self, workers: int | None = None):
        self.workers = (
            workers if workers is not None else (os.cpu_count() or 1)
        )
        self._pool = None
        # per-packer shm namespace: close() may sweep it wholesale
        # without touching a sibling packer's live segments (cmd_train
        # holds one packer per split in the same process)
        self._shm_prefix = (
            f"{_SHM_PREFIX}-{os.getpid()}-{next(_PACKER_TOKENS)}-"
        )

    def _init_args(self) -> tuple:
        raise NotImplementedError

    def _pack_inline(self, item):
        raise NotImplementedError

    def _get_pool(self):
        if self._pool is None and self.workers > 1:
            _sweep_stale()
            ctx = mp.get_context("spawn")
            self._pool = ctx.Pool(
                self.workers,
                initializer=type(self)._init_fn,
                initargs=(*self._init_args(), self._shm_prefix),
            )
        return self._pool

    def __enter__(self):
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def close(self) -> None:
        if self._pool is not None:
            self._pool.terminate()
            self._pool.join()
            self._pool = None
            # terminate() discarded queued ("shm", name, ...) results and
            # killed mid-pack workers; their segments are unreachable now
            # — sweep this packer's whole namespace
            _sweep_prefix(self._shm_prefix)

    def _drain(self, pending) -> None:
        """Receive-and-unlink every outstanding shm result. Runs when the
        consumer abandons `pack` mid-stream: terminate() would discard
        the queued ("shm", name, ...) tuples, and with track=False
        nothing else ever unlinks those segments — they would pile up in
        /dev/shm across runs until packing silently degrades to the
        pickle fallback."""
        for r in pending:
            try:
                result = r.get()
            except Exception:
                continue
            if result[0] == "shm":
                _discard_shm(result[1])

    def pack(self, plans: Iterable) -> Iterator:
        """Pack plans across the pool, yielding in plan order.

        Dispatch is windowed (2*workers outstanding): imap's task
        handler would eagerly consume every plan and let the pool race
        a whole epoch ahead of a training-paced consumer, pinning each
        packed batch's bytes in /dev/shm (or, once that fills and
        _shm_create degrades to the pickle fallback, in the parent's
        result queue) until received. The window keeps every worker busy
        while bounding in-flight results to a constant.
        """
        pool = self._get_pool()
        if pool is None:
            for plan in plans:
                yield self._pack_inline(plan)
            return
        window = 2 * self.workers
        it = iter(plans)
        pending: deque = deque()
        task = type(self)._task_fn
        receive = type(self)._receive_fn

        def fill() -> None:
            while len(pending) < window:
                plan = next(it, None)
                if plan is None:
                    return
                pending.append(pool.apply_async(task, (plan,)))

        try:
            fill()
            while pending:
                result = pending.popleft().get()
                fill()  # keep workers fed while the consumer trains
                yield receive(result)
        except BaseException:
            self._drain(pending)
            raise


class MpPacker(_PoolPacker):
    """A reusable spawn-pool packer bound to one GraphSpec corpus;
    `shard_bucket_batches` can be called every epoch."""

    _init_fn = staticmethod(_init_worker)
    _task_fn = staticmethod(_pack_one)
    _receive_fn = staticmethod(_receive)

    def __init__(
        self,
        graphs: Iterable[GraphSpec],
        workers: int | None = None,
        add_self_loops: bool = True,
    ):
        super().__init__(workers)
        self.graphs = (
            graphs if isinstance(graphs, Sequence) else list(graphs)
        )
        self.add_self_loops = add_self_loops

    def _init_args(self) -> tuple:
        return (self.graphs, self.add_self_loops)

    def _pack_inline(self, plan: BatchPlan) -> GraphBatch:
        return pack_plan(self.graphs, plan, self.add_self_loops)

    def shard_bucket_batches(
        self,
        num_shards: int,
        num_graphs: int,
        node_budget: int,
        edge_budget: int,
        oversized: str = "drop",
        stats: dict | None = None,
        select: Sequence[int] | None = None,
    ) -> Iterator[GraphBatch]:
        """Drop-in parallel `graphs.shard_bucket_batches` over this
        corpus: identical plans, identical batches, packed on the pool.

        `select` restricts (and orders) the pass to a subset of the
        bound corpus by index — e.g. a per-epoch undersample selection —
        without re-pickling graphs to the workers: plans are built over
        the selection, then remapped to corpus indices before shipping.
        """
        if select is None:
            src = self.graphs
        else:
            select = [int(i) for i in select]
            src = [self.graphs[i] for i in select]
        plans = plan_shard_bucket_batches(
            src, num_shards, num_graphs, node_budget, edge_budget,
            self.add_self_loops, oversized, stats,
        )
        if select is not None:
            plans = (
                dataclasses.replace(
                    p,
                    shard_indices=tuple(
                        tuple(select[i] for i in idxs)
                        for idxs in p.shard_indices
                    ),
                )
                for p in plans
            )
        yield from self.pack(plans)


def mp_shard_bucket_batches(
    graphs: Sequence[GraphSpec],
    num_shards: int,
    num_graphs: int,
    node_budget: int,
    edge_budget: int,
    add_self_loops: bool = True,
    oversized: str = "drop",
    stats: dict | None = None,
    workers: int | None = None,
) -> Iterator[GraphBatch]:
    """One-shot convenience: pool lifetime = one pass over the corpus.
    Prefer a long-lived MpPacker when packing every epoch."""
    with MpPacker(graphs, workers, add_self_loops) as packer:
        yield from packer.shard_bucket_batches(
            num_shards, num_graphs, node_budget, edge_budget, oversized,
            stats,
        )


class TextMpPacker(_PoolPacker):
    """Spawn-pool collater for bucketed TextBatch streams — the text-path
    analog of MpPacker (ISSUE 2). The parent runs the cheap sequential
    planner (`data/text.py:plan_bucketed_batches`), workers materialize
    `collate_plan` (numpy-heavy padding + aligned graph packing), and
    batches return through the same shared-memory protocol: TextBatch
    leaves plus "graphs."-prefixed nested GraphBatch leaves in one
    segment. Order and content are bit-identical to inline collation
    (same plans, same collater; pinned by
    tests/test_text_bucketing.py:test_text_pool_and_cache_roundtrip).
    """

    _init_fn = staticmethod(_init_text_worker)
    _task_fn = staticmethod(_collate_text_one)
    _receive_fn = staticmethod(_receive_text)

    def __init__(
        self,
        token_ids_by_id,
        labels_by_id,
        graphs_by_id,
        pad_id: int = PAD_ID_BY_FAMILY["roberta"],
        workers: int | None = None,
    ):
        super().__init__(workers)
        self.token_ids_by_id = dict(token_ids_by_id)
        self.labels_by_id = dict(labels_by_id)
        self.graphs_by_id = dict(graphs_by_id)
        self.pad_id = int(pad_id)

    def _init_args(self) -> tuple:
        return (
            self.token_ids_by_id, self.labels_by_id, self.graphs_by_id,
            self.pad_id,
        )

    def _pack_inline(self, plan: TextBatchPlan) -> TextBatch:
        return collate_plan(
            plan, self.token_ids_by_id, self.labels_by_id,
            self.graphs_by_id, self.pad_id,
        )

    def bucketed_batches(
        self,
        example_ids: Sequence[int],
        buckets: Sequence[int],
        token_budget: int,
        num_shards: int,
        node_budget: int,
        edge_budget: int,
        lengths: Sequence[int] | None = None,
        stats: dict | None = None,
    ) -> Iterator[TextBatch]:
        """Drop-in parallel `data.text.bucketed_collate_batches` over the
        bound corpus: identical plans, identical batches, collated on
        the pool. `example_ids` restricts (and orders) the pass — e.g. a
        per-epoch undersample selection — without re-pickling the corpus
        to the workers."""
        if lengths is None:
            from deepdfa_tpu.data.text import lengths_for

            lengths = lengths_for(
                self.token_ids_by_id, example_ids, self.pad_id
            )
        plans = plan_bucketed_batches(
            lengths, example_ids, buckets, token_budget, num_shards,
            node_budget, edge_budget, stats=stats,
        )
        yield from self.pack(plans)
