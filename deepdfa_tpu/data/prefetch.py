"""Background batch prefetch: overlap host batch assembly + H2D copies
with device compute.

The reference overlaps input work with GPU compute via DataLoader worker
processes (DDFA/sastvd/linevd/datamodule.py:110-141). The TPU-native
equivalent is a bounded producer thread: batch ASSEMBLY (python/numpy
bucketing, tokenization, feature attach) runs ahead of the training step,
and — when a `place` function is given — `jax.device_put` runs in the
producer too, so the H2D copy of batch k+1 rides under the device compute
of batch k. Python threads suffice: assembly is numpy-bound (releases the
GIL) and device_put is an async dispatch.

Semantics guarantee: a pure reordering in time. The consumer sees exactly
the same elements in exactly the same order as iterating the source
directly, so step counts and numerics are unchanged (pinned by
tests/test_prefetch.py).
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable, Iterator, TypeVar

T = TypeVar("T")

_DONE = object()


class _Failure:
    def __init__(self, exc: BaseException):
        self.exc = exc


def prefetch(
    source: Iterable[T],
    size: int = 2,
    place: Callable[[T], T] | None = None,
) -> Iterator[T]:
    """Iterate `source` through a `size`-deep background queue.

    place: optional callable run in the producer thread on each element
    (typically a sharded jax.device_put); its result is what the consumer
    receives. Exceptions from the source or from `place` re-raise at the
    consumer's next pull. `size <= 0` disables prefetching entirely and
    iterates inline (the knob's off position).
    """
    if size <= 0:
        for item in source:
            yield place(item) if place is not None else item
        return

    q: queue.Queue = queue.Queue(maxsize=size)
    stop = threading.Event()

    def put_or_stop(item) -> bool:
        """Bounded put that gives up when the consumer abandoned the
        iterator — every producer put (including the terminal sentinel /
        failure) must respect `stop`, or an abandoned consumer leaks a
        blocked thread pinning device-resident batches."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def producer() -> None:
        try:
            for item in source:
                if place is not None:
                    item = place(item)
                if not put_or_stop(item):
                    return
            put_or_stop(_DONE)
        except BaseException as e:  # re-raised consumer-side
            put_or_stop(_Failure(e))

    t = threading.Thread(target=producer, daemon=True, name="batch-prefetch")
    t.start()
    try:
        while True:
            item = q.get()
            if item is _DONE:
                return
            if isinstance(item, _Failure):
                raise item.exc
            yield item
    finally:
        stop.set()


def device_placer(mesh, spec=None) -> Callable[[T], T]:
    """A `place` fn that device_puts a batch pytree with a NamedSharding
    (leading axis over dp by default) — static pytree metadata fields are
    untouched, so jit cache keys are unchanged."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, spec if spec is not None else P("dp"))

    def place(batch):
        return jax.device_put(batch, sharding)

    return place
