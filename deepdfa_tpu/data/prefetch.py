"""Background batch prefetch: overlap host batch assembly + H2D copies
with device compute.

The reference overlaps input work with GPU compute via DataLoader worker
processes (DDFA/sastvd/linevd/datamodule.py:110-141). The TPU-native
equivalent is a bounded producer pool: batch ASSEMBLY (python/numpy
bucketing, tokenization, feature attach) runs ahead of the training step,
and — when a `place` function is given — `jax.device_put` runs in the
producers too, so the H2D copy of batch k+1 rides under the device compute
of batch k. Python threads suffice: assembly is numpy-bound (releases the
GIL) and device_put is an async dispatch; CPU-bound first-epoch packing
goes to processes instead (data/mp_pack.py).

Semantics guarantee: a pure reordering in time. The consumer sees exactly
the same elements in exactly the same order as iterating the source
directly — with ANY number of producers — so step counts and numerics are
unchanged (pinned by tests/test_prefetch.py).

Stage instrumentation: pass a `PipelineStats` and every stage's wall time
accumulates into it — `load`/`pack` (source pulls, attributed via
`source_stage`), `place` (H2D), `wait` (consumer blocked on the queue).
The train loops surface these per epoch so end-to-end regressions are
attributable to host vs device (docs/input_pipeline.md). The same stages
emit cat="input" spans into the unified trace (deepdfa_tpu/obs/trace.py,
docs/observability.md) — no-ops unless tracing is enabled.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Iterable, Iterator, TypeVar

from deepdfa_tpu.obs import trace as obs_trace

T = TypeVar("T")

#: producer threads poll the stop flag at this period when blocked; the
#: abandon path joins them with a small multiple of it
_POLL = 0.1
_JOIN_TIMEOUT = 2.0


@dataclasses.dataclass
class PipelineStats:
    """Per-stage wall-time counters for the host input pipeline.

    Counters are cumulative seconds of per-stage work (summed across
    producer threads, so with overlap they can exceed wall-clock):

    - ``load_seconds``: reading pre-packed batches (cache replay / store
      reads) — source pulls when ``source_stage="load"``.
    - ``pack_seconds``: live batch assembly (bucketing + padding) —
      source pulls when ``source_stage="pack"`` (the default).
    - ``place_seconds``: sharded ``jax.device_put`` (H2D copy dispatch).
    - ``wait_seconds``: consumer blocked waiting for the next batch — the
      number that indicts the host when it stays high.

    Token counters (the sequence-bucketing observables,
    docs/input_pipeline.md): text-batch consumers call ``add_tokens``
    per batch so the epoch records can report real-token throughput and
    ``padding_waste`` — the fraction of padded token slots the device
    computes that carry no real token.
    """

    load_seconds: float = 0.0
    pack_seconds: float = 0.0
    place_seconds: float = 0.0
    wait_seconds: float = 0.0
    produced: int = 0
    consumed: int = 0
    real_tokens: int = 0
    padded_tokens: int = 0
    rows: int = 0

    def __post_init__(self):
        self._lock = threading.Lock()

    def add(self, stage: str, seconds: float, produced: int = 0) -> None:
        with self._lock:
            setattr(
                self, f"{stage}_seconds",
                getattr(self, f"{stage}_seconds") + seconds,
            )
            self.produced += produced

    def add_tokens(self, real: int, padded: int, rows: int = 0) -> None:
        """Account one text batch: `real` non-pad tokens in valid rows,
        `padded` total token slots (the full static shape — padding rows
        are device compute too), `rows` valid rows."""
        with self._lock:
            self.real_tokens += int(real)
            self.padded_tokens += int(padded)
            self.rows += int(rows)

    def padding_waste(self) -> float:
        """1 - real/padded: the fraction of computed token slots that
        hold padding (0.0 when no tokens were accounted)."""
        if self.padded_tokens <= 0:
            return 0.0
        return 1.0 - self.real_tokens / self.padded_tokens

    def wait_fraction(self, total_seconds: float) -> float:
        """Fraction of a consumer's wall-clock spent blocked on input."""
        return self.wait_seconds / total_seconds if total_seconds > 0 else 0.0

    def record(self) -> dict[str, float]:
        out = {
            "load_seconds": round(self.load_seconds, 4),
            "pack_seconds": round(self.pack_seconds, 4),
            "place_seconds": round(self.place_seconds, 4),
            "wait_seconds": round(self.wait_seconds, 4),
            "produced": self.produced,
            "consumed": self.consumed,
        }
        if self.padded_tokens:
            out.update(
                real_tokens=self.real_tokens,
                padded_tokens=self.padded_tokens,
                rows=self.rows,
                padding_waste=round(self.padding_waste(), 4),
            )
        return out


def prefetch(
    source: Iterable[T],
    size: int = 2,
    place: Callable[[T], T] | None = None,
    producers: int = 1,
    stats: PipelineStats | None = None,
    source_stage: str = "pack",
) -> Iterator[T]:
    """Iterate `source` through a `size`-deep background pipeline.

    place: optional callable run in a producer thread on each element
    (typically a sharded jax.device_put); its result is what the consumer
    receives. Exceptions from the source or from `place` re-raise at the
    consumer's next pull. `size <= 0` disables prefetching entirely and
    iterates inline (the knob's off position).

    producers: worker threads. Source pulls are always serialized (one
    iterator), but `place` — and anything the source itself hands off —
    runs concurrently, so >1 helps when H2D placement is a significant
    slice of the budget. Output order is the source order regardless.

    stats/source_stage: optional `PipelineStats` instrumentation; source
    pull time lands in `pack_seconds` ("pack", live assembly) or
    `load_seconds` ("load", cache replay).

    Abandoning the iterator (break / close) stops and JOINS the producer
    threads, so no background thread outlives the consumer pinning
    device-resident batches.
    """
    if source_stage not in ("pack", "load"):
        raise ValueError(f"source_stage={source_stage!r}")
    if stats is None:
        stats = PipelineStats()
    if size <= 0:
        it = iter(source)
        while True:
            t0 = time.perf_counter()
            with obs_trace.span(source_stage, cat="input"):
                try:
                    item = next(it)
                except StopIteration:
                    return
            stats.add(source_stage, time.perf_counter() - t0, produced=1)
            if place is not None:
                t0 = time.perf_counter()
                with obs_trace.span("place", cat="input"):
                    item = place(item)
                stats.add("place", time.perf_counter() - t0)
            stats.consumed += 1
            yield item

    producers = max(1, int(producers))
    src_iter = iter(source)
    src_lock = threading.Lock()
    cond = threading.Condition()
    buf: dict[int, T] = {}
    state = {
        "next_in": 0,  # next index a producer will pull (under src_lock)
        "next_out": 0,  # next index the consumer yields (under cond)
        "done_at": None,  # source length once exhausted
        "error": None,  # first failure, re-raised in source order
        "stop": False,
    }

    def producer() -> None:
        while True:
            if state["stop"]:
                return
            # bounded run-ahead, gated at the CLAIM: a claimed item is
            # pulled and placed (device_put) before it reaches buf, so
            # gating only the insert would let every producer hold one
            # extra device-resident batch beyond the `size` bound the
            # prefetch knob promises (size + producers + 1 resident)
            with cond:
                while (
                    not state["stop"]
                    and state["done_at"] is None
                    and state["error"] is None
                    and state["next_in"] >= state["next_out"] + max(1, size)
                ):
                    cond.wait(_POLL)
            with src_lock:
                if state["stop"]:
                    return
                if state["done_at"] is not None or state["error"] is not None:
                    return
                if state["next_in"] >= state["next_out"] + max(1, size):
                    # another producer claimed the slot while this one
                    # was between the gate and the lock — re-wait
                    continue
                idx = state["next_in"]
                t0 = time.perf_counter()
                try:
                    with obs_trace.span(source_stage, cat="input"):
                        item = next(src_iter)
                except StopIteration:
                    with cond:
                        state["done_at"] = idx
                        cond.notify_all()
                    return
                except BaseException as e:
                    with cond:
                        if state["error"] is None:
                            state["error"] = (idx, e)
                        cond.notify_all()
                    return
                state["next_in"] = idx + 1
                stats.add(source_stage, time.perf_counter() - t0, produced=1)
            if place is not None:
                try:
                    t0 = time.perf_counter()
                    with obs_trace.span("place", cat="input"):
                        item = place(item)
                    stats.add("place", time.perf_counter() - t0)
                except BaseException as e:
                    with cond:
                        if (
                            state["error"] is None
                            or state["error"][0] > idx
                        ):
                            state["error"] = (idx, e)
                        cond.notify_all()
                    return
            with cond:
                # idx was claimed inside the run-ahead window and
                # next_out only grows, so the insert never needs to wait
                if state["stop"]:
                    return
                buf[idx] = item
                cond.notify_all()

    threads = [
        threading.Thread(
            target=producer, daemon=True, name=f"batch-prefetch-{i}"
        )
        for i in range(producers)
    ]
    for t in threads:
        t.start()

    try:
        while True:
            with obs_trace.span("wait", cat="input"), cond:
                t0 = time.perf_counter()
                while True:
                    nxt = state["next_out"]
                    if nxt in buf:
                        item = buf.pop(nxt)
                        state["next_out"] = nxt + 1
                        cond.notify_all()
                        break
                    # nxt is not buffered here; if the failure hit nxt (or
                    # earlier), no producer will ever deliver it — re-raise
                    err = state["error"]
                    if err is not None and err[0] <= nxt:
                        stats.add("wait", time.perf_counter() - t0)
                        raise err[1]
                    if state["done_at"] is not None and nxt >= state["done_at"]:
                        stats.add("wait", time.perf_counter() - t0)
                        return
                    cond.wait(_POLL)
                stats.add("wait", time.perf_counter() - t0)
            stats.consumed += 1
            yield item
    finally:
        state["stop"] = True
        with cond:
            buf.clear()  # drop refs so device batches free promptly
            cond.notify_all()
        for t in threads:
            # a producer can only be blocked in cond polls (bounded) or a
            # source pull; join with a timeout so an abandoned consumer
            # never hangs — a daemon thread stuck in the source dies with
            # the process either way
            t.join(timeout=_JOIN_TIMEOUT)


def device_placer(mesh, spec=None) -> Callable[[T], T]:
    """A `place` fn that device_puts a batch pytree through the unified
    sharding layer (parallel/sharding.py:place_batch — leading axis over
    dp by default, so a [num_shards, ...] batch spreads its logical
    shards across the mesh) — static pytree metadata fields are
    untouched, so jit cache keys are unchanged.

    Batches whose leading axis is not divisible by the sharded mesh axes
    raise a clear ValueError naming the offending leaf, instead of XLA's
    opaque sharding failure deep inside device_put/jit.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from deepdfa_tpu.parallel import sharding as sharding_mod

    spec = spec if spec is not None else P("dp")
    # built ONCE per placer: the hot path below does zero per-batch
    # sharding construction (place_batch's single-sharding fast path)
    named = NamedSharding(mesh, spec)
    first = spec[0] if len(spec) else None
    axes = (
        (first,) if isinstance(first, str)
        else tuple(first) if isinstance(first, (tuple, list))
        else ()
    )
    divisor = 1
    for ax in axes:
        divisor *= mesh.shape.get(ax, 1)

    def _validate(batch) -> None:
        for path, leaf in jax.tree_util.tree_flatten_with_path(batch)[0]:
            shape = getattr(leaf, "shape", None)
            if not shape:
                continue
            if shape[0] % divisor:
                name = jax.tree_util.keystr(path)
                raise ValueError(
                    f"batch leaf {name} has leading axis {shape[0]}, not "
                    f"divisible by mesh axes {axes} (size {divisor}) — "
                    f"pack with a num_shards this divides (train CLI: "
                    f"check train.mesh.dp/num_shards vs the batcher)"
                )

    def place(batch):
        if divisor > 1:
            _validate(batch)
        return sharding_mod.place_batch(mesh, batch, named)

    return place
