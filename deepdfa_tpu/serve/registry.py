"""Model registry for online inference (docs/serving.md).

Turns a training run directory into an inference-ready handle: the run's
saved `config.json` (the same manifest `cmd_test`/`cmd_localize` restore
against), a params-only checkpoint restore
(`train/checkpoint.py:restore_for_inference` — never the optimizer), and
the abstract-dataflow vocabularies the run extracted with, digest-pinned
so a checkpoint can never be silently served against features it was not
trained on.

Hot swap: `maybe_reload()` re-reads the checkpoint manifest between
batches (serve/batcher.py calls it via the batcher's `on_batch` hook)
and swaps the params pytree in place when the tracked tag advanced to a
newer step. Param shapes are fixed by the config, so a swap never
invalidates the AOT bucket executables — the next batch simply runs with
the new weights, zero recompiles.

Three model families restore through the same interface:
  - "deepdfa"  — the flagship GGNN (checkpoints/, DeepDFA.from_config)
  - "combined" — RoBERTa-family transformer+graph (checkpoints-combined/)
  - "t5"       — the CodeT5-style defect head (checkpoints-combined/)
The combined/t5 families need the tokenizer + encoder config the run was
trained with; the CLI builds those exactly as `cmd_train_combined` does
and passes them in (`model_cfg`).
"""

from __future__ import annotations

import hashlib
import json
import logging
import threading
from pathlib import Path
from typing import Any, Callable

from deepdfa_tpu.core import Config, config as config_mod
from deepdfa_tpu.serve import quant

logger = logging.getLogger(__name__)

#: checkpoint subdirectory per model family (the training CLI's layout)
CKPT_DIR_BY_FAMILY = {
    "deepdfa": "checkpoints",
    "combined": "checkpoints-combined",
    "t5": "checkpoints-combined",
}


class RegistryError(RuntimeError):
    """Registry-level restore failure with an operator-grade message."""


#: model knobs excluded from the digest: pure compiled-program LOWERING
#: choices (how the fused GGNN step tiles/scatters/accumulates), never
#: parameter shapes or feature semantics — a tuned layout
#: (deepdfa_tpu/tune/, docs/tuning.md) applied at serve time must not
#: refuse hot swaps against the run's untuned saved config. scatter and
#: accum move scores only within their documented numerics tolerances
#: (docs/ggnn_kernel.md) — shape/feature compatibility, the digest's
#: scope, is untouched.
_LAYOUT_ONLY_MODEL_KEYS = (
    "ggnn_kernel_block_nodes", "ggnn_kernel_block_edges",
    "ggnn_kernel_scatter", "ggnn_kernel_accum", "ggnn_kernel_unroll",
)

#: data knobs equally excluded: sequence-bucket edges shape PADDING
#: layout (which batch signatures compile), never tokenization or
#: feature semantics — a re-train that picked up tuned interior edges
#: must not refuse hot swaps against servers started on the old config
_LAYOUT_ONLY_DATA_KEYS = ("seq_buckets",)


def config_digest(cfg: Config) -> str:
    """Digest of the config sections that determine parameter shapes and
    feature semantics (model + data). Two runs with equal digests produce
    checkpoints that are shape-compatible AND feature-compatible, which
    is the hot-swap admission criterion."""
    d = config_mod._to_dict(cfg)
    model = {
        k: v for k, v in d["model"].items()
        if k not in _LAYOUT_ONLY_MODEL_KEYS
    }
    data = {
        k: v for k, v in d["data"].items()
        if k not in _LAYOUT_ONLY_DATA_KEYS
    }
    payload = json.dumps(
        {"model": model, "data": data}, sort_keys=True
    )
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def config_drift(saved: dict, current: dict, prefix: str = "") -> list[str]:
    """Dotted keys (model./data. sections) whose values differ between a
    run's saved config.json and the config being served with — the
    'clear error naming the mismatched config keys' payload."""
    out: list[str] = []
    for section in ("model", "data"):
        a, b = saved.get(section, {}), current.get(section, {})
        out.extend(_dict_drift(a, b, f"{section}."))
    return out


def _dict_drift(a: Any, b: Any, prefix: str) -> list[str]:
    if isinstance(a, dict) and isinstance(b, dict):
        out = []
        for k in sorted(set(a) | set(b)):
            out.extend(_dict_drift(a.get(k), b.get(k), f"{prefix}{k}."))
        return out
    # tuples round-trip to lists through json
    na = list(a) if isinstance(a, (list, tuple)) else a
    nb = list(b) if isinstance(b, (list, tuple)) else b
    return [] if na == nb else [prefix.rstrip(".")]


def load_run_config(run_dir: Path) -> Config:
    """The run's saved config.json — the manifest checkpoint restores
    must be built against (same contract as cli `_load_run_config`)."""
    path = Path(run_dir) / "config.json"
    if not path.exists():
        raise RegistryError(
            f"{path} not found — the run directory must hold the "
            f"config.json the training CLI writes (is {run_dir} a run?)"
        )
    cfg = config_mod.load(path)
    config_mod.validate(cfg)
    return cfg


def load_vocabs(cfg: Config) -> tuple[dict, str]:
    """The run's abstract-dataflow vocabularies + their content digest.

    The file name encodes the full FeatureSpec, so a feat-spec drift
    between extraction and serving is a missing file here (named), and a
    re-extraction under the same spec changes the digest — which the
    hot-swap admission check and /healthz both surface."""
    from deepdfa_tpu.core import paths
    from deepdfa_tpu.frontend.vocab import AbsDfVocab

    vocab_path = (
        paths.processed_dir(cfg.data.dataset)
        / f"vocab{cfg.data.feat.name}.json"
    )
    if not vocab_path.exists():
        raise RegistryError(
            f"vocab file {vocab_path} not found — serving needs the "
            f"vocabularies the checkpoint was trained with (run `extract` "
            f"with the same data.feat.* settings, or fix data.feat.* to "
            f"match the training run)"
        )
    raw = vocab_path.read_bytes()
    vocabs = {
        k: AbsDfVocab.from_json(v) for k, v in json.loads(raw).items()
    }
    want = cfg.data.feat.input_dim
    for k, v in vocabs.items():
        if v.input_dim > want:
            raise RegistryError(
                f"vocab subkey {k!r} input_dim {v.input_dim} exceeds "
                f"data.feat.limit_all+2={want} — the vocab on disk was "
                f"built with different data.feat.limit_all than this "
                f"config declares"
            )
    return vocabs, hashlib.sha256(raw).hexdigest()[:16]


def serve_mesh(cfg: Config):
    """The serving mesh when `cfg.serve.sharded` (parallel/sharding.py,
    docs/sharding.md): multi-host init + a mesh over `cfg.serve.mesh`
    axes; None otherwise — the historical single-device placement, so
    the default path is untouched. One helper so `score`/`serve`/scan/
    cascade-stage-2/fleet-replica registries all build the mesh the
    same way."""
    if not getattr(cfg.serve, "sharded", False):
        return None
    from deepdfa_tpu.parallel import make_mesh
    from deepdfa_tpu.parallel import sharding as sharding_mod

    sharding_mod.init_runtime()
    mesh = make_mesh(cfg.serve.mesh)
    sharding_mod.publish_mesh(mesh)
    return mesh


class ModelRegistry:
    """Restores and holds the serving state for one run.

    Thread-safe params access: the batcher's device thread reads
    `params()` per batch while `maybe_reload()` may swap underneath —
    the swap is a single reference assignment under the lock, so a batch
    sees either the old or the new weights, never a mix.
    """

    def __init__(
        self,
        run_dir: str | Path,
        family: str = "deepdfa",
        checkpoint: str = "best",
        cfg: Config | None = None,
        model_cfg: Any = None,
        mesh: Any = None,
        flywheel_tag: str = "incumbent",
    ):
        """mesh: an optional serve mesh (cfg.serve.sharded +
        cfg.serve.mesh, parallel/sharding.py) — restored params commit
        under the family's resolved sharding map (train.mesh.rules
        prepend), so a checkpoint written on ANY training topology
        serves sharded without a reshape step; hot swaps re-place under
        the same map (zero recompiles, the executables' input shardings
        never change)."""
        if family not in CKPT_DIR_BY_FAMILY:
            raise RegistryError(
                f"unknown model family {family!r}; "
                f"known: {sorted(CKPT_DIR_BY_FAMILY)}"
            )
        self.run_dir = Path(run_dir)
        self.family = family
        self.checkpoint = checkpoint
        #: flywheel role tag (docs/flywheel.md): "incumbent" for the
        #: serving fleet, "candidate" for a shadow-ride registry — the
        #: tag rides /healthz + heartbeats so diag and the promotion
        #: controller can tell the two apart on the record
        self.flywheel_tag = str(flywheel_tag)
        # `tag@int8` = the quantized alternate entry for `tag`
        # (serve/quant.py): same manifest pointer, int8/bf16 pytree
        self.base_checkpoint, self.quant_mode = (
            quant.split_checkpoint_tag(checkpoint)
        )
        self.quant_drift: float | None = None
        self.quant_bytes_fraction: float | None = None
        self.cfg = cfg if cfg is not None else load_run_config(self.run_dir)
        self.model_cfg = model_cfg
        self.tokenizer = None
        self.serve_max_length: int | None = None
        if family in ("combined", "t5") and model_cfg is None:
            # combined/t5 runs that saved a model_cfg.json manifest
            # (train-combined writes one; serve/cascade.py owns the
            # format) are self-describing — rebuild the tokenizer +
            # encoder config from it instead of requiring CLI args
            from deepdfa_tpu.serve import cascade as cascade_mod

            setup = cascade_mod.try_load_model_setup(self.run_dir, family)
            if setup is None:
                raise RegistryError(
                    f"family {family!r} needs the encoder model_cfg the "
                    f"run was trained with: pass model_cfg, or train a "
                    f"run that saved {cascade_mod.MODEL_CFG_MANIFEST} "
                    f"(train-combined writes it)"
                )
            self.tokenizer, self.model_cfg, self.serve_max_length = setup
        if family == "deepdfa" and self.cfg.model.label_style != "graph":
            raise RegistryError(
                f"serving supports model.label_style='graph' only "
                f"(got {self.cfg.model.label_style!r})"
            )
        self.config_digest = config_digest(self.cfg)
        self.vocabs, self.vocab_digest = load_vocabs(self.cfg)
        self.mesh = mesh
        self.sharding_map = None
        if mesh is not None:
            from deepdfa_tpu.parallel import sharding as sharding_mod

            self.sharding_map = sharding_mod.sharding_map_for(
                family,
                model_cfg=self.model_cfg,
                mesh_shape=dict(mesh.shape),
                extra_rules=getattr(self.cfg.train.mesh, "rules", ()),
            )
            if self.quant_mode and self.sharding_map.rules:
                # quantized trees replace weight leaves with
                # {int8, scale} marker dicts, so path rules written for
                # the fp32 layout ('*/kernel') never match them — the
                # entry serves REPLICATED over the mesh. Loud, not
                # silent: the operator asked for both and gets the
                # unsupported-combination truth
                logger.warning(
                    "serve.sharded + %s: sharding-map rules do not "
                    "match quantized leaf paths (…/kernel/int8); the "
                    "quantized entry serves replicated over the mesh",
                    self.checkpoint,
                )
        self._lock = threading.Lock()
        self._params = None
        self._loaded_step: int | None = None
        self._loaded_manifest_sig: tuple | None = None
        self._model = None
        self._apply: Callable | None = None
        self._mgr = None
        self.reloads = 0
        self.swaps = 0
        #: bumped (under _lock) by every operator swap/rollback so the
        #: hot-swap poller's restore — which runs OUTSIDE the lock —
        #: can detect a swap that landed mid-restore and discard its
        #: now-stale params instead of clobbering the swap's
        self._swap_generation = 0
        #: the rollback stash (fleet rollout, docs/fleet.md): the
        #: previously-serving (checkpoint, step, params) kept on device
        #: after an operator swap so `rollback()` is one reference
        #: assignment, no disk round trip
        self._prev: tuple[str, int | None, Any] | None = None
        self._load_initial()

    # -- construction --------------------------------------------------------

    @property
    def ckpt_dir(self) -> Path:
        return self.run_dir / CKPT_DIR_BY_FAMILY[self.family]

    def _abstract_params(self):
        """A params pytree of the right structure/shapes to restore into
        (concrete but throwaway — init at the serving dims)."""
        import jax

        if self.family == "deepdfa":
            from deepdfa_tpu.graphs.batch import pack
            from deepdfa_tpu.models import DeepDFA

            model = DeepDFA.from_config(
                self.cfg.model, input_dim=self.cfg.data.feat.input_dim
            )
            dummy = pack(
                [], 1, 64, 256, feat_width=self._feat_width(),
                etypes=self.cfg.model.n_etypes > 1,
            )
            params = model.init(jax.random.key(0), dummy)
            self._model = model
            return jax.device_get(params)
        from deepdfa_tpu.models import combined as cmb
        from deepdfa_tpu.models import t5 as t5m

        init = (
            t5m.init_defect_params if self.family == "t5" else cmb.init_params
        )
        return jax.device_get(init(self.model_cfg, jax.random.key(0)))

    def _feat_width(self) -> int:
        from deepdfa_tpu.graphs.batch import NUM_SUBKEY_FEATS

        width = NUM_SUBKEY_FEATS
        if getattr(self.cfg.model, "struct_feats", False):
            from deepdfa_tpu.frontend.structfeat import STRUCT_VOCAB

            width += len(STRUCT_VOCAB)
        return width

    def _manifest_sig(self, base: str | None = None) -> tuple | None:
        """(step, mtime_ns) of a tag per the manifest — the cheap
        change detector maybe_reload polls. `base` defaults to the
        tracked base checkpoint; a rollout swap passes its target so
        the shared tracked-tag state is never touched (maybe_reload
        may be polling it concurrently from the batcher thread)."""
        base = self.base_checkpoint if base is None else base
        path = self.ckpt_dir / "manifest.json"
        try:
            st = path.stat()
            manifest = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError):
            return None
        if base == "best":
            entry = manifest.get("best")
        elif base == "last":
            entry = manifest.get("last")
        else:
            entry = next(
                (e for e in reversed(manifest.get("history", []))
                 if e.get("tag") == base),
                None,
            )
        step = entry.get("step", -1) if entry else -1
        return (step, st.st_mtime_ns)

    def _restore(self, tag: str | None = None):
        """One params restore with operator-grade errors; `tag` defaults
        to the tracked base checkpoint (a rollout swap passes the new
        tag through the same path)."""
        from deepdfa_tpu.train.checkpoint import (
            CheckpointManager,
            CheckpointMismatch,
        )

        if self._mgr is None:
            if not self.ckpt_dir.is_dir():
                raise RegistryError(
                    f"no checkpoint directory {self.ckpt_dir} — family "
                    f"{self.family!r} expects the "
                    f"{CKPT_DIR_BY_FAMILY[self.family]}/ layout the "
                    f"training CLI writes"
                )
            self._mgr = CheckpointManager(self.ckpt_dir)
        target = self._abstract_params()
        # elastic placement (docs/sharding.md): plain entries restore
        # STRAIGHT onto the serving mesh's resolved shardings; @int8
        # entries restore to host first — quantization rewrites the tree
        # before placement (_maybe_quantize -> _place)
        shardings = None
        if self.sharding_map is not None and not self.quant_mode:
            shardings = self.sharding_map.shardings(self.mesh, target)
        try:
            return self._mgr.restore_for_inference(
                tag if tag is not None else self.base_checkpoint,
                target, shardings=shardings,
            )
        except CheckpointMismatch as e:
            # name the CONFIG keys when the saved run config can tell us
            saved_path = self.run_dir / "config.json"
            drift: list[str] = []
            if saved_path.exists():
                drift = config_drift(
                    json.loads(saved_path.read_text()),
                    config_mod._to_dict(self.cfg),
                )
            if drift:
                raise RegistryError(
                    f"checkpoint restore failed; config keys differ from "
                    f"the run's saved config.json: {drift} — ({e})"
                ) from e
            raise RegistryError(str(e)) from e

    def _ledger_params(self) -> None:
        """Per-registry-entry parameter bytes into the HBM ledger (the
        co-serving capacity signal: how many entries fit one chip) —
        no-op unless the efficiency ledger is on."""
        from deepdfa_tpu.obs import ledger as obs_ledger

        if obs_ledger.enabled():
            obs_ledger.record_params(
                f"{self.family}:{self.run_dir.name}:{self.checkpoint}",
                self._params,
            )
            obs_ledger.record_memory("registry_load")

    # -- quantized entries (serve/quant.py, docs/cascade.md) -----------------

    def _score_fn(self):
        """(f32 params, packed batch) -> probs, per family — the one
        probability rule the serving executables compile, reused eagerly
        by the quantization calibration pass."""
        import jax

        if self.family == "deepdfa":
            model = self._model

            def score(params, batch):
                return jax.nn.sigmoid(model.apply(params, batch))

            return score
        mc = self.model_cfg
        if self.family == "t5":
            from deepdfa_tpu.models import t5 as t5m

            def score(params, batch):
                logits = t5m.defect_forward(
                    mc, params, batch.input_ids,
                    graph_batch=batch.graphs, has_graph=batch.has_graph,
                    dropout_key=None,
                )
                return jax.nn.softmax(logits)[:, 1]

            return score
        from deepdfa_tpu.models import combined as cmb

        def score(params, batch):
            logits = cmb.forward(
                mc, params, batch.input_ids,
                graph_batch=batch.graphs, has_graph=batch.has_graph,
                dropout_key=None,
            )
            return jax.nn.softmax(logits)[:, 1]

        return score

    def _calibration_batches(self) -> list:
        """Deterministic random calibration inputs for the drift check —
        one packed batch with real (non-padding) rows, so every weight
        the quantizer touched contributes to the measured drift."""
        n = max(1, int(self.cfg.serve.quant_calibration_samples))
        if self.family == "deepdfa":
            return [quant.calibration_graph_batch(
                n, node_budget=1024, edge_budget=4096,
                feat_width=self._feat_width(),
                input_dim=self.cfg.data.feat.input_dim,
                etypes=self.cfg.model.n_etypes > 1,
                n_etypes=self.cfg.model.n_etypes,
            )]
        enc = self.model_cfg.encoder
        cap = int(
            getattr(enc, "max_sequence_length", 0)
            or getattr(enc, "max_position_embeddings", 36) - 4
        )
        return [quant.calibration_text_batch(
            rows=n, seq_len=max(8, min(32, cap)),
            vocab_size=int(enc.vocab_size),
            pad_id=int(getattr(enc, "pad_token_id", 0)),
            node_budget=1024, edge_budget=4096,
        )]

    def _maybe_quantize(self, params):
        """fp32 restore -> the serving tree. Plain entries pass through;
        @int8 entries quantize, measure calibration drift against the
        fp32 params, and REFUSE past the configured bound (the offending
        param paths named, CheckpointMismatch style)."""
        if not self.quant_mode:
            return params
        qtree = quant.quantize_params(params)
        bound = float(self.cfg.serve.quant_drift_bound)
        try:
            drift = quant.check_drift(
                self._score_fn(), params, qtree,
                self._calibration_batches(), bound,
            )
        except quant.QuantizationError as e:
            raise RegistryError(str(e)) from e
        report = quant.quant_report(params, qtree)
        self.quant_drift = drift
        self.quant_bytes_fraction = round(report.bytes_fraction, 4)
        logger.info(
            "quantized %s: %.0f -> %.0f param bytes (%.1f%%), "
            "calibration drift %.2e (bound %g)",
            self.checkpoint, report.bytes_fp32, report.bytes_quant,
            100 * report.bytes_fraction, drift, bound,
        )
        return qtree

    @property
    def params_transform(self):
        """The in-jit dequantization hook the executors fold into their
        compiled programs; None for plain fp32 entries."""
        return quant.dequantize_params if self.quant_mode else None

    def _place(self, params):
        """Commit restored params: under the resolved sharding map on a
        serve mesh, or the historical single-device placement."""
        import jax

        if self.sharding_map is not None:
            return self.sharding_map.place(self.mesh, params)
        return jax.device_put(params)

    def _load_initial(self) -> None:
        sig = self._manifest_sig()
        params = self._maybe_quantize(self._restore())
        with self._lock:
            self._params = self._place(params)
            self._loaded_manifest_sig = sig
            self._loaded_step = sig[0] if sig else None
        self._ledger_params()

    # -- serving surface -----------------------------------------------------

    def params(self):
        with self._lock:
            return self._params

    @property
    def model(self):
        """The flax module (deepdfa family only)."""
        return self._model

    def maybe_reload(self) -> bool:
        """Poll the manifest; hot-swap params when the tracked tag moved.

        Called between batches (never mid-batch). A checkpoint whose
        config/vocab digest changed is REFUSED (logged, old params keep
        serving) — shape-compatible-by-luck weights from a different
        recipe must not slide in silently."""
        sig = self._manifest_sig()
        if sig is None or sig == self._loaded_manifest_sig:
            return False
        with self._lock:
            gen = self._swap_generation
        try:
            new_cfg = load_run_config(self.run_dir)
            if config_digest(new_cfg) != self.config_digest:
                drift = config_drift(
                    config_mod._to_dict(new_cfg),
                    config_mod._to_dict(self.cfg),
                )
                logger.warning(
                    "hot-swap refused: run config changed (%s); still "
                    "serving step %s", drift, self._loaded_step,
                )
                self._loaded_manifest_sig = sig  # don't re-log every poll
                return False
            _, vocab_digest = load_vocabs(self.cfg)
            if vocab_digest != self.vocab_digest:
                logger.warning(
                    "hot-swap refused: vocab digest changed (%s -> %s); "
                    "still serving step %s",
                    self.vocab_digest, vocab_digest, self._loaded_step,
                )
                self._loaded_manifest_sig = sig
                return False
            params = self._maybe_quantize(self._restore())
            with self._lock:
                if self._swap_generation != gen:
                    # an operator swap/rollback landed while this
                    # poller was restoring outside the lock: its params
                    # and identity win — committing ours would silently
                    # revert the swap while /healthz reports it landed
                    logger.warning(
                        "hot-swap discarded: an operator checkpoint "
                        "swap landed mid-reload; serving %r step %s",
                        self.checkpoint, self._loaded_step,
                    )
                    return False
                self._params = self._place(params)
                self._loaded_manifest_sig = sig
                self._loaded_step = sig[0]
            self._ledger_params()
            self.reloads += 1
            from deepdfa_tpu.obs import metrics as obs_metrics

            obs_metrics.REGISTRY.counter("serve/hot_swaps").inc()
            logger.info("hot-swapped to checkpoint step %s", sig[0])
            return True
        except (RegistryError, OSError) as e:
            # a half-written checkpoint mid-poll must not kill serving
            logger.warning("hot-swap attempt failed (%s); keeping params", e)
            return False

    def _measure_swap_drift(self, old_params, new_params) -> float:
        """Max |P_new - P_old| over the deterministic calibration
        batches — the PR-12 drift machinery (serve/quant.py) pointed at
        a rollout instead of a quantizer. Quantized trees dequantize
        eagerly first, exactly as the serving executables do."""
        score_fn = self._score_fn()
        batches = self._calibration_batches()
        old_f32 = quant.dequantize_params(old_params)
        return quant.max_prob_drift(score_fn, old_f32, new_params, batches)

    def swap_checkpoint(
        self, checkpoint: str, drift_bound: float | None = None
    ) -> dict:
        """Operator-driven hot swap to a DIFFERENT checkpoint tag — the
        zero-downtime rollout path (fleet/rollout.py, docs/fleet.md).

        Rollback-capable: the previously-serving (tag, step, params) is
        stashed on device, so `rollback()` restores it with one
        reference assignment. Param shapes are fixed by the config, so
        neither direction ever invalidates an AOT executable (zero
        recompiles — the census the rollout drill pins).

        `drift_bound` gates on calibration score drift: max
        |P_new - P_old| over deterministic calibration batches past the
        bound REFUSES the swap (RegistryError naming the drift; the old
        params keep serving untouched) — a bad checkpoint halts a
        rollout at the first replica instead of serving wrong scores.

        Returns {checkpoint, checkpoint_step, previous, drift}."""
        sig = self._manifest_sig_for(checkpoint)
        base, quant_mode = quant.split_checkpoint_tag(checkpoint)
        if quant_mode != self.quant_mode:
            raise RegistryError(
                f"swap cannot change quantization mode "
                f"({self.checkpoint!r} -> {checkpoint!r}); start a "
                f"replica with the target mode instead"
            )
        try:
            restored = self._restore(base)
        except FileNotFoundError as e:
            raise RegistryError(str(e)) from e
        new_params = self._maybe_quantize(restored)
        with self._lock:
            old_params = self._params
        drift = None
        if drift_bound is not None:
            drift = self._measure_swap_drift(old_params, new_params)
            if drift > float(drift_bound):
                raise RegistryError(
                    f"swap to {checkpoint!r} REFUSED: calibration score "
                    f"drift {drift:.4g} exceeds the bound "
                    f"{float(drift_bound):g} — the new checkpoint does "
                    f"not score like the serving one (still serving "
                    f"{self.checkpoint!r} step {self._loaded_step})"
                )
        placed = self._place(new_params)
        with self._lock:
            self._prev = (
                self.checkpoint, self._loaded_step, old_params
            )
            previous = self.checkpoint
            self._params = placed
            self.checkpoint = checkpoint
            self.base_checkpoint = base
            self._loaded_manifest_sig = sig
            self._loaded_step = sig[0] if sig else None
            self._swap_generation += 1  # fences in-flight hot-reloads
        self._ledger_params()
        self.swaps += 1
        from deepdfa_tpu.obs import metrics as obs_metrics

        obs_metrics.REGISTRY.counter("serve/hot_swaps").inc()
        logger.info(
            "swapped %s -> %s (step %s, drift %s)",
            previous, checkpoint, self._loaded_step, drift,
        )
        return {
            "checkpoint": checkpoint,
            "checkpoint_step": self._loaded_step,
            "previous": previous,
            "drift": drift,
        }

    def rollback(self) -> dict | None:
        """Undo the last `swap_checkpoint`: the stashed params resume
        serving with one reference assignment (no disk, no recompiles).
        Returns the restored identity, or None when there is nothing to
        roll back to."""
        with self._lock:
            if self._prev is None:
                return None
            checkpoint, step, params = self._prev
        sig = self._manifest_sig_for(checkpoint)
        with self._lock:
            if self._prev is None:
                return None
            rolled_from = self.checkpoint
            self._prev = None
            self._params = params
            self.checkpoint = checkpoint
            self.base_checkpoint, _ = quant.split_checkpoint_tag(
                checkpoint
            )
            self._loaded_step = step
            self._loaded_manifest_sig = sig
            self._swap_generation += 1  # fences in-flight hot-reloads
        from deepdfa_tpu.obs import metrics as obs_metrics

        obs_metrics.REGISTRY.counter("serve/hot_swaps").inc()
        logger.warning(
            "rolled back %s -> %s (step %s)",
            rolled_from, checkpoint, step,
        )
        return {
            "checkpoint": checkpoint,
            "checkpoint_step": step,
            "rolled_back_from": rolled_from,
        }

    def _manifest_sig_for(self, checkpoint: str) -> tuple | None:
        """`_manifest_sig` for an arbitrary tag (the swap target) —
        read-only: mutating the tracked tag here would race the
        hot-swap poller on the batcher thread."""
        base, _ = quant.split_checkpoint_tag(checkpoint)
        return self._manifest_sig(base)

    def info(self) -> dict:
        """/healthz payload: what is serving, from where, pinned how."""
        out = {
            "family": self.family,
            "run_dir": str(self.run_dir),
            "checkpoint": self.checkpoint,
            "checkpoint_step": self._loaded_step,
            "config_digest": self.config_digest,
            "vocab_digest": self.vocab_digest,
            "hot_swaps": self.reloads,
        }
        if self.flywheel_tag != "incumbent":
            # non-default role only: the incumbent /healthz payload
            # stays byte-identical with the flywheel off
            out["flywheel_tag"] = self.flywheel_tag
        if self._prev is not None:
            # the rollback stash (fleet rollout): what one `rollback()`
            # would resume serving
            out["previous_checkpoint"] = self._prev[0]
        if self.quant_mode:
            out.update(
                quantized=self.quant_mode,
                quant_drift=self.quant_drift,
                quant_drift_bound=self.cfg.serve.quant_drift_bound,
                quant_param_bytes_fraction=self.quant_bytes_fraction,
            )
        if self.mesh is not None:
            from deepdfa_tpu.parallel import sharding as sharding_mod

            out["sharded"] = True
            out["mesh"] = sharding_mod.mesh_record(self.mesh)
            out["sharding_map"] = self.sharding_map.describe()
        return out
