"""HTTP scoring endpoint + offline batch scorer (docs/serving.md).

stdlib-only (`http.server.ThreadingHTTPServer`) — the serving tax we
actually care about is device batching, not framework features:

  POST /score   {"code": "<C function>"}   -> {"ok": true, "prob": p}
  GET  /healthz                            -> model/checkpoint identity
  GET  /stats                              -> queue/latency/cache stats

Request lifecycle (see docs/serving.md for the diagram):
  HTTP thread -> frontend (cached feature extraction) -> bounded queue
  -> bucket scheduler (serve/batcher.py) -> AOT executable -> response.
Admission control maps to status codes: a full queue is 429, an
unparseable function 422, an over-budget graph 413 — the caller learns
to back off or split, the device never sees the bad request.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from typing import Any

from deepdfa_tpu.obs import metrics as obs_metrics
from deepdfa_tpu.serve.batcher import (
    DynamicBatcher,
    GgnnExecutor,
    QueueFull,
    RequestTooLarge,
)
from deepdfa_tpu.serve.frontend import FrontendError, RequestPreprocessor
from deepdfa_tpu.serve.registry import ModelRegistry

logger = logging.getLogger(__name__)


class ScoringService:
    """Registry + frontend + batcher wired per the serve config — the
    one object both the HTTP server and the offline `score` CLI drive."""

    def __init__(self, registry: ModelRegistry, cfg=None):
        cfg = cfg if cfg is not None else registry.cfg
        self.cfg = cfg
        scfg = cfg.serve
        self.registry = registry
        node_budget = scfg.node_budget or cfg.data.batch.node_budget
        edge_budget = scfg.edge_budget or cfg.data.batch.edge_budget
        if registry.family != "deepdfa":
            raise NotImplementedError(
                "ScoringService wires the flagship GGNN family; combined/"
                "t5 serving drives CombinedExecutor directly (see "
                "docs/serving.md)"
            )
        self.frontend = RequestPreprocessor(
            cfg, registry.vocabs,
            use_joern=scfg.use_joern,
            cache_entries=scfg.feature_cache_entries,
        )
        self.executor = GgnnExecutor(
            registry.model, registry.params,
            node_budget=node_budget, edge_budget=edge_budget,
            max_batch_graphs=scfg.max_batch_graphs,
            feat_width=registry._feat_width(),
            etypes=cfg.model.n_etypes > 1,
        )
        self.batcher = DynamicBatcher(
            self.executor,
            queue_limit=scfg.queue_limit,
            max_batch_delay_s=scfg.max_batch_delay_ms / 1000.0,
            on_batch=(registry.maybe_reload if scfg.hot_swap else None),
        )
        self.warmup_report = self.executor.warmup()
        self.lowerings_after_warmup = self.executor.jit_lowerings()

    def submit_code(self, code: str):
        """frontend + enqueue; the caller waits on the returned request."""
        spec = self.frontend.features(code)
        return self.batcher.submit(spec)

    def steady_state_recompiles(self) -> int:
        return self.executor.jit_lowerings() - self.lowerings_after_warmup

    def healthz(self) -> dict:
        info = self.registry.info()
        info.update(
            warmed_signatures=[
                list(s) for s in self.executor.signatures()
            ],
            jit_lowerings=self.executor.jit_lowerings(),
            steady_state_recompiles=self.steady_state_recompiles(),
        )
        return info

    def stats(self) -> dict:
        out = self.batcher.stats()
        out["feature_cache_entries"] = len(self.frontend.cache)
        snap = obs_metrics.REGISTRY.snapshot()
        out["serve"] = {
            k[len("serve/"):]: v
            for k, v in snap.items()
            if k.startswith("serve/")
        }
        return out

    def serve_record(self) -> dict:
        """One run-log record of the serve metrics (flattened by
        `flatten_scalars` into the `serve/*` tags SCHEMA declares)."""
        snap = obs_metrics.REGISTRY.snapshot()
        return {
            "serve": {
                k[len("serve/"):]: v
                for k, v in snap.items()
                if k.startswith("serve/")
            }
        }

    def start(self) -> None:
        self.batcher.start()

    def close(self) -> None:
        self.batcher.close()
        self.frontend.close()


def write_serve_log(run_dir, records) -> Path:
    """Append serve records to <run_dir>/serve_log.jsonl — the log
    scripts/check_obs_schema.py --serve-smoke validates against SCHEMA."""
    path = Path(run_dir) / "serve_log.jsonl"
    with path.open("a") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
    return path


def score_texts(
    service: ScoringService, texts: list[tuple[str, str]],
    timeout_s: float = 120.0,
) -> list[dict]:
    """Offline scoring of (name, code) pairs through the online path.

    Frontend failures become per-row errors, never a crash; the batcher
    groups whatever was admitted exactly as live traffic would."""
    rows: list[dict] = []
    payloads: list[tuple[dict, Any]] = []
    for name, code in texts:
        row = {"name": name}
        rows.append(row)  # input order preserved
        try:
            payloads.append((row, service.frontend.features(code)))
        except (FrontendError, RequestTooLarge) as e:
            row.update(ok=False, error=str(e))
    reqs = service.batcher.score_all([spec for _, spec in payloads])
    for (row, _), req in zip(payloads, reqs):
        try:
            row.update(ok=True, prob=req.wait(timeout_s))
        except Exception as e:  # noqa: BLE001 - per-row fault isolation
            row.update(ok=False, error=str(e))
    return rows


class _Handler(BaseHTTPRequestHandler):
    service: ScoringService = None  # set by make_server
    request_timeout_s: float = 60.0

    def _reply(self, status: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # route through logging, not stderr
        logger.debug("http: " + fmt, *args)

    def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
        if self.path == "/healthz":
            self._reply(200, self.service.healthz())
        elif self.path == "/stats":
            self._reply(200, self.service.stats())
        else:
            self._reply(404, {"error": f"no route {self.path}"})

    def do_POST(self):  # noqa: N802
        if self.path != "/score":
            self._reply(404, {"error": f"no route {self.path}"})
            return
        try:
            n = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(n) or b"{}")
            code = payload["code"]
        except (ValueError, KeyError) as e:
            self._reply(400, {"error": f"bad request: {e}"})
            return
        t0 = time.monotonic()
        try:
            req = self.service.submit_code(code)
            prob = req.wait(self.request_timeout_s)
        except QueueFull as e:
            self._reply(429, {"error": str(e)})
            return
        except RequestTooLarge as e:
            self._reply(413, {"error": str(e)})
            return
        except FrontendError as e:
            self._reply(422, {"error": str(e)})
            return
        except TimeoutError as e:
            self._reply(504, {"error": str(e)})
            return
        self._reply(
            200,
            {
                "ok": True,
                "prob": prob,
                "latency_ms": round((time.monotonic() - t0) * 1e3, 3),
            },
        )


def make_server(
    service: ScoringService, host: str = "127.0.0.1", port: int = 0
) -> ThreadingHTTPServer:
    """Bound (not yet serving) HTTP server; port 0 picks an ephemeral
    port (server.server_address[1] has the real one)."""
    handler = type("BoundHandler", (_Handler,), {"service": service})
    return ThreadingHTTPServer((host, port), handler)


def serve_forever(service: ScoringService, host: str, port: int) -> None:
    service.start()
    httpd = make_server(service, host, port)
    real_port = httpd.server_address[1]
    print(
        json.dumps({
            "serving": True, "host": host, "port": real_port,
            **service.healthz(),
        }),
        flush=True,
    )
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.server_close()
        service.close()


class BackgroundServer:
    """In-process server on an ephemeral port (smoke mode + tests)."""

    def __init__(self, service: ScoringService, host: str = "127.0.0.1"):
        self.service = service
        service.start()
        self.httpd = make_server(service, host, 0)
        self.host = host
        self.port = self.httpd.server_address[1]
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )
        self._thread.start()

    def request(self, method: str, path: str, payload: dict | None = None):
        import http.client

        conn = http.client.HTTPConnection(self.host, self.port, timeout=60)
        body = json.dumps(payload) if payload is not None else None
        conn.request(
            method, path, body=body,
            headers={"Content-Type": "application/json"} if body else {},
        )
        resp = conn.getresponse()
        data = json.loads(resp.read() or b"{}")
        conn.close()
        return resp.status, data

    def close(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        self._thread.join(timeout=10)
        self.service.close()
