"""HTTP scoring endpoint + offline batch scorer (docs/serving.md,
docs/slo.md).

stdlib-only (`http.server.ThreadingHTTPServer`) — the serving tax we
actually care about is device batching, not framework features:

  POST /score    {"code": "<C function>"}  -> {"ok": true, "prob": p,
                 "request_id": ...}; {"trace": true} opts into a
                 per-stage latency echo
  GET  /healthz  model/checkpoint identity; ?deep=1 adds a bounded
                 backend probe (obs/health.py — wedge detection)
  GET  /stats    queue/latency/cache stats + rolling SLO windows
  GET  /metrics  Prometheus text exposition (obs/slo.py)

Request lifecycle (see docs/serving.md for the diagram):
  HTTP thread -> frontend (cached feature extraction) -> bounded queue
  -> bucket scheduler (serve/batcher.py) -> AOT executable -> response.
Admission control maps to status codes: a full queue is 429, an
unparseable function 422, an over-budget graph 413 — the caller learns
to back off or split, the device never sees the bad request.

Observability (this PR's tentpole): every request gets an id at
ingress; its frontend/queue/device spans are flow-linked in the merged
Chrome trace; the final status + stage attribution feed the SLO engine
and (with `serve.request_log`) one `{"request": {...}}` entry per
request in serve_log.jsonl.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from typing import Any

from deepdfa_tpu.obs import (
    health as obs_health,
    ledger as obs_ledger,
    metrics as obs_metrics,
    slo as obs_slo,
    trace as obs_trace,
)
from deepdfa_tpu.serve.batcher import (
    DynamicBatcher,
    GgnnExecutor,
    QueueFull,
    RequestTooLarge,
    ScoreRequest,
    new_request_id,
)
from deepdfa_tpu.serve import frontend as serve_frontend
from deepdfa_tpu.serve.frontend import FrontendError, RequestPreprocessor
from deepdfa_tpu.serve.registry import ModelRegistry

logger = logging.getLogger(__name__)


class RequestLog:
    """Thread-safe per-request appender to serve_log.jsonl
    (`serve.request_log`). ONE handle held open, flushed per entry (the
    RunLogger rule): a crash loses at most the in-flight line, and the
    log stays tail-able while serving."""

    def __init__(self, path: str | Path):
        self.path = Path(path)
        self._lock = threading.Lock()
        self._file = self.path.open("a")

    def append(self, entry: dict) -> None:
        line = json.dumps(entry)
        with self._lock:
            self._file.write(line + "\n")
            self._file.flush()

    def close(self) -> None:
        with self._lock:
            if not self._file.closed:
                self._file.close()


class ScoringService:
    """Registry + frontend + batcher wired per the serve config — the
    one object both the HTTP server and the offline `score` CLI drive.

    Family dispatch: the flagship GGNN gets the graph frontend + GgnnExecutor
    (+ optional line localizer); combined/t5 registries get the tokenizer
    frontend + CombinedExecutor (serve/cascade.py owns those parts) — the
    same service surface either way, which is what lets the fleet replica
    co-serve all three families and the cascade run its stage 2 through
    the identical machinery."""

    def __init__(self, registry: ModelRegistry, cfg=None):
        cfg = cfg if cfg is not None else registry.cfg
        self.cfg = cfg
        scfg = cfg.serve
        self.registry = registry
        node_budget = scfg.node_budget or cfg.data.batch.node_budget
        edge_budget = scfg.edge_budget or cfg.data.batch.edge_budget
        # the quantized-entry dequant hook (serve/quant.py); getattr so
        # registry-shaped stubs (scripts/bench_load.py) keep working
        params_transform = getattr(registry, "params_transform", None)
        # the serve mesh (serve.sharded, parallel/sharding.py): params
        # are registry-committed under the sharding map; the executors
        # replicate batches over the same mesh
        mesh = getattr(registry, "mesh", None)
        # tuned layouts (deepdfa_tpu/tune/, docs/tuning.md): with
        # tune.enabled the executors consult tuned.json AT WARMUP —
        # serve ladder rungs and seq-bucket edges fitted to the
        # observed distribution replace the pow2 defaults when (and
        # only when) a record matches this hardware generation; any
        # mismatch falls back loudly inside record_for_config. Never
        # touched on the request path.
        self.tuned: dict | None = None
        tuned_rungs = None
        tuned_buckets = None
        tcfg = getattr(cfg, "tune", None)
        if tcfg is not None and getattr(tcfg, "enabled", False):
            from deepdfa_tpu.tune import cache as tune_cache

            rec = tune_cache.record_for_config(
                cfg, node_budget, edge_budget
            )
            if rec is not None:
                tuned_rungs = tune_cache.serve_rungs_from(
                    rec, scfg.max_batch_graphs
                )
                tuned_buckets = tune_cache.seq_edges_from(rec)
                self.tuned = {
                    "hardware": rec.get("hardware"),
                    "serve_rungs": (
                        list(tuned_rungs) if tuned_rungs else None
                    ),
                    "seq_buckets": (
                        list(tuned_buckets) if tuned_buckets else None
                    ),
                }
        self.localizer = None
        if registry.family == "deepdfa":
            # the ONE process-wide content-keyed feature store: a repo
            # scan (deepdfa_tpu/scan/) warm-fills the cache online
            # requests hit, and vice versa — never two sibling stores
            self.frontend = RequestPreprocessor(
                cfg, registry.vocabs,
                use_joern=scfg.use_joern,
                cache=serve_frontend.shared_cache(
                    scfg.feature_cache_entries
                ),
            )
            self.executor = GgnnExecutor(
                registry.model, registry.params,
                node_budget=node_budget, edge_budget=edge_budget,
                max_batch_graphs=scfg.max_batch_graphs,
                feat_width=registry._feat_width(),
                etypes=cfg.model.n_etypes > 1,
                params_transform=params_transform,
                mesh=mesh,
                ladder=tuned_rungs,
            )
            # line-level localization (serve.lines): the attribution
            # program AOT-compiled over the SAME warmup ladder, so
            # {"lines": true} requests never trigger a steady-state
            # lowering either
            if scfg.lines:
                from deepdfa_tpu.serve.localize import GgnnLocalizer

                self.localizer = GgnnLocalizer(
                    registry.model, registry.params,
                    node_budget=node_budget, edge_budget=edge_budget,
                    sizes=self.executor.sizes,
                    method=scfg.lines_method, n_steps=scfg.lines_steps,
                    top_k=scfg.lines_top_k,
                    feat_width=registry._feat_width(),
                    etypes=cfg.model.n_etypes > 1,
                    params_transform=params_transform,
                    mesh=mesh,
                    pipeline_depth=scfg.pipeline_depth,
                )
        else:
            from deepdfa_tpu.serve import cascade as cascade_mod

            self.frontend, self.executor = (
                cascade_mod.build_combined_service_parts(
                    registry, cfg, node_budget, edge_budget,
                    seq_buckets=tuned_buckets,
                )
            )
        # cascade mode (serve.cascade, docs/cascade.md): the stage-2
        # stack is its own full ScoringService (combined/t5 family) with
        # its own AOT warmup ladder; built BEFORE the lowering census so
        # zero-steady-state-recompiles covers both family ladders
        self.cascade = None
        stages = obs_slo.STAGES
        if scfg.cascade and registry.family == "deepdfa":
            from deepdfa_tpu.serve import cascade as cascade_mod

            self.cascade = cascade_mod.CascadeStage2.from_config(
                cfg, registry.run_dir
            )
            stages = obs_slo.STAGES + obs_slo.CASCADE_STAGES
        self.slo = obs_slo.SloEngine(
            windows=scfg.slo_windows,
            max_samples=scfg.slo_window_samples,
            stages=stages,
        )
        self.health = obs_health.BackendHealth()
        self.request_log: RequestLog | None = (
            RequestLog(registry.run_dir / "serve_log.jsonl")
            if scfg.request_log else None
        )
        self.batcher = DynamicBatcher(
            self.executor,
            queue_limit=scfg.queue_limit,
            max_batch_delay_s=scfg.max_batch_delay_ms / 1000.0,
            on_batch=(self._poll_hot_swap if scfg.hot_swap else None),
            slo=self.slo,
            pipeline_depth=scfg.pipeline_depth,
        )
        self.warmup_report = self.executor.warmup()
        if self.localizer is not None:
            self.warmup_report.update(self.localizer.warmup())
        self.lowerings_after_warmup = self._jit_lowerings()

    def _jit_lowerings(self) -> int:
        """Lowerings across EVERY compiled surface this service owns
        (score + line attribution + the cascade's stage-2 ladder) — the
        zero-steady-state-recompiles guard covers the whole serving
        process, not just the score ladder."""
        n = self.executor.jit_lowerings()
        if self.localizer is not None:
            n += self.localizer.jit_lowerings()
        if self.cascade is not None:
            n += self.cascade.jit_lowerings()
        return n

    def _poll_hot_swap(self) -> None:
        if self.registry.maybe_reload():
            self.slo.observe_hot_swap()

    def submit_code(
        self,
        code: str,
        request_id: str | None = None,
        want_feats: bool = False,
    ):
        """frontend + enqueue; the caller waits on the returned request.

        The id assigned here (or passed from the HTTP ingress) travels
        with the request: the frontend span carries it, the queue-wait
        and device spans flow-link to it, and `finish_request` logs it.
        `want_feats=True` additionally returns the cached extraction
        (spec + node lines) so the lines path can attribute without a
        second frontend trip."""
        rid = request_id or new_request_id()
        t0 = time.perf_counter()
        try:
            with obs_trace.span("frontend", cat="serve", request_id=rid):
                obs_trace.flow("request", rid, "s", cat="serve")
                feats = self.frontend.features_full(code)
            req = self.batcher.submit(
                feats.spec, request_id=rid,
                frontend_s=time.perf_counter() - t0,
            )
            return (req, feats) if want_feats else req
        except Exception as e:
            # a rejected request (422/413/429) still did frontend work;
            # ride the measurement on the exception so the epilogue can
            # ingest it — under overload the rejected population is
            # exactly the one the stage windows must not exclude
            e.frontend_s = time.perf_counter() - t0
            raise

    def finish_request(
        self,
        request_id: str,
        status: int,
        latency_s: float | None,
        req: ScoreRequest | None = None,
        frontend_s: float | None = None,
        extra_stages: dict | None = None,
        log_fields: dict | None = None,
    ) -> dict:
        """The single request epilogue (HTTP handler AND offline drive):
        feed the SLO windows, append the per-request serve_log entry,
        and return the stage attribution (the opt-in `/score` echo).
        `extra_stages` carries cascade stage seconds
        (cascade_stage1/cascade_stage2); `log_fields` carries scalar
        verdict fields for the log entry (stage, stage1_prob, ...)."""
        stages = {
            "frontend": (
                req.frontend_s if req is not None else frontend_s
            ),
            "queue": req.queue_wait_s if req is not None else None,
            "device": req.device_s if req is not None else None,
        }
        self.slo.observe_request(
            status, latency_s,
            frontend_s=stages["frontend"], queue_s=stages["queue"],
            device_s=stages["device"],
            extra=extra_stages,
        )
        if extra_stages:
            stages.update(extra_stages)
        ms = {
            f"{k}_ms": round(1e3 * v, 3)
            for k, v in stages.items() if v is not None
        }
        if self.request_log is not None:
            entry = {
                "id": request_id, "status": int(status),
                "t_unix": round(time.time(), 3), **ms,
            }
            if latency_s is not None:
                entry["latency_ms"] = round(1e3 * latency_s, 3)
            if req is not None and req.batch_size is not None:
                entry["batch_size"] = req.batch_size
            if log_fields:
                entry.update(log_fields)
            self.request_log.append({"request": entry})
        return ms

    def cascade_decide(
        self,
        code: str,
        prob1: float,
        request_id: str,
        req: ScoreRequest | None = None,
    ):
        """The cascade verdict for one stage-1 score: (final prob,
        response fields, extra SLO stage seconds). cascade_stage1 is the
        stage-1 request's full latency (the screen's cost); stage 2 adds
        cascade_stage2 when escalated."""
        prob, info, extra = self.cascade.decide(
            code, prob1, request_id=request_id
        )
        if req is not None and req.latency_s is not None:
            extra = {"cascade_stage1": req.latency_s, **extra}
        return prob, info, extra

    def attribute_lines(self, feats, request_id: str | None = None):
        """Per-line attributions for ONE extracted function through the
        AOT localizer (the `{"lines": true}` half of a request); raises
        when localization is not enabled."""
        if self.localizer is None:
            raise FrontendError(
                "line attributions are disabled; start the server with "
                "serve.lines=true"
            )
        with obs_trace.span(
            "localize", cat="serve", request_id=request_id
        ):
            [(_, lines)] = self.localizer.attribute([feats])
        return lines

    def steady_state_recompiles(self) -> int:
        return self._jit_lowerings() - self.lowerings_after_warmup

    def healthz(self, deep: bool = False) -> dict:
        info = self.registry.info()
        info.update(
            warmed_signatures=[
                list(s) for s in self.executor.signatures()
            ],
            jit_lowerings=self._jit_lowerings(),
            steady_state_recompiles=self.steady_state_recompiles(),
            lines=self.localizer is not None,
        )
        # which message-passing lowering is serving (operators need to
        # know before reading latency numbers): the Pallas-fused step's
        # per-signature census, or the lax path when the knob is off
        if self.registry.family == "deepdfa":
            from deepdfa_tpu.nn import ggnn_kernel as _ggnn_kernel

            info["ggnn_kernel"] = bool(
                getattr(self.registry.cfg.model, "ggnn_kernel", False)
            )
            if info["ggnn_kernel"]:
                info["ggnn_kernel_signatures"] = (
                    _ggnn_kernel.signature_stats()
                )
                # the serving unroll mode (per_step | fused) — a fused
                # config that fell back reports its REQUEST here and
                # the fallback in ggnn_kernel/fused_fallbacks
                info["ggnn_kernel_unroll"] = getattr(
                    self.registry.cfg.model, "ggnn_kernel_unroll",
                    "per_step",
                )
        if self.localizer is not None:
            info["lines_method"] = self.localizer.method
        if self.tuned is not None:
            # which tuned layout is serving (docs/tuning.md): operators
            # need to know before reading the ladder-waste gauge
            info["tuned"] = self.tuned
        if self.cascade is not None:
            info["cascade"] = self.cascade.info()
        if deep:
            # bounded subprocess compile-and-execute of the DEFAULT
            # backend (obs/health.py) — the wedged-compile-service
            # detector; never on the request path, only when an
            # operator/prober asks for it
            info["backend"] = self.health.probe(
                timeout_s=self.cfg.serve.health_probe_timeout_s
            )
        elif self.health.last() is not None:
            info["backend"] = self.health.last()
        return info

    def stats(self) -> dict:
        out = self.batcher.stats()
        out["feature_cache_entries"] = len(self.frontend.cache)
        snap = obs_metrics.REGISTRY.snapshot()
        out["serve"] = {
            k[len("serve/"):]: v
            for k, v in snap.items()
            if k.startswith("serve/")
        }
        out["slo"] = self.slo.snapshot()
        if self.cascade is not None:
            out["cascade"] = self.cascade.counters()
        led = obs_ledger.snapshot_or_none()
        if led is not None:
            # the device efficiency view (docs/efficiency.md): per-
            # signature compiled cost, rolling MFU, HBM watermarks
            out["ledger"] = led
        return out

    def metrics_text(self) -> str:
        """The `/metrics` body: the process-wide registry + the rolling
        SLO windows, one Prometheus text exposition
        (scripts/check_obs_schema.py --metrics validates it). The
        efficiency ledger refreshes its derived `ledger/*` gauges
        (rolling MFU / roofline position) right before the scrape."""
        obs_ledger.publish_gauges()
        return obs_slo.registry_exposition() + self.slo.exposition()

    def serve_record(self) -> dict:
        """One run-log record of the serve metrics (flattened by
        `flatten_scalars` into the `serve/*` + `serve_slo/*` +
        `backend/*` tags SCHEMA declares)."""
        snap = obs_metrics.REGISTRY.snapshot()
        record = {
            "serve": {
                k[len("serve/"):]: v
                for k, v in snap.items()
                if k.startswith("serve/")
            },
            "serve_slo": self.slo.snapshot(),
            # pipelined serve_log evidence: check_obs_schema requires
            # the serve/pipeline/* tags whenever this is > 0
            "serve_pipeline_depth": self.batcher.pipeline_depth,
        }
        backend = {
            k[len("backend/"):]: v
            for k, v in snap.items()
            if k.startswith("backend/")
        }
        if backend:
            record["backend"] = backend
        if self.cascade is not None:
            # the cascade section validate_cascade_log requires:
            # escalation accounting + the stage-2 recompile census
            record["cascade"] = {
                **self.cascade.counters(),
                "stage2_steady_state_recompiles": (
                    self.cascade.service.steady_state_recompiles()
                ),
            }
        led = obs_ledger.snapshot_or_none()
        if led is not None:
            record["ledger"] = led
        return record

    def start(self) -> None:
        self.batcher.start()
        if self.cascade is not None:
            self.cascade.start()

    def close(self) -> None:
        self.batcher.close()
        self.frontend.close()
        if self.cascade is not None:
            self.cascade.close()
        if self.request_log is not None:
            self.request_log.close()


def write_serve_log(run_dir, records) -> Path:
    """Append serve records to <run_dir>/serve_log.jsonl — the log
    scripts/check_obs_schema.py --serve-smoke validates against SCHEMA."""
    path = Path(run_dir) / "serve_log.jsonl"
    with path.open("a") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
    return path


def score_texts(
    service: ScoringService, texts: list[tuple[str, str]],
    timeout_s: float = 120.0,
) -> list[dict]:
    """Offline scoring of (name, code) pairs through the online path.

    Frontend failures become per-row errors, never a crash; the batcher
    groups whatever was admitted exactly as live traffic would. Every
    row passes through the same `finish_request` epilogue as HTTP
    traffic (status-code analog per outcome), so the SLO windows and
    the request log cover offline drives too."""
    rows: list[dict] = []
    payloads: list[tuple[dict, Any, str, float, str]] = []
    for name, code in texts:
        row = {"name": name}
        rows.append(row)  # input order preserved
        rid = new_request_id()
        row["request_id"] = rid
        t0 = time.perf_counter()
        try:
            with obs_trace.span("frontend", cat="serve", request_id=rid):
                obs_trace.flow("request", rid, "s", cat="serve")
                spec = service.frontend.features(code)
            payloads.append(
                (row, spec, rid, time.perf_counter() - t0, code)
            )
        except (FrontendError, RequestTooLarge) as e:
            status = 422 if isinstance(e, FrontendError) else 413
            row.update(ok=False, error=str(e))
            service.finish_request(
                rid, status, time.perf_counter() - t0,
                frontend_s=time.perf_counter() - t0,
            )
    reqs = service.batcher.score_all(
        [spec for _, spec, _, _, _ in payloads],
        request_ids=[rid for _, _, rid, _, _ in payloads],
        frontend_seconds=[fs for _, _, _, fs, _ in payloads],
    )
    # cascade mode (docs/cascade.md): collect the stage-1 verdicts
    # first, then escalate the whole uncertain band through the stage-2
    # batcher's deterministic offline drive in one grouped pass
    escalate: list[tuple[dict, ScoreRequest, str, str]] = []
    done: list[tuple[dict, ScoreRequest, str, dict, dict]] = []
    for (row, _, rid, _, code), req in zip(payloads, reqs):
        try:
            prob1 = req.wait(timeout_s)
        except Exception as e:  # noqa: BLE001 - per-row fault isolation
            row.update(ok=False, error=str(e))
            # same status-code analog per outcome as the HTTP path
            if isinstance(e, RequestTooLarge):
                status = 413
            elif isinstance(e, TimeoutError):
                status = 504
            else:
                status = 500
            service.finish_request(rid, status, req.latency_s, req=req)
            continue
        casc = service.cascade
        if casc is None:
            row.update(ok=True, prob=prob1)
            service.finish_request(rid, 200, req.latency_s, req=req)
            continue
        # the SAME screen verdict the HTTP handler uses (band + shed +
        # counter semantics live in ONE place, CascadeStage2.screen)
        should_escalate, fields = casc.screen(prob1)
        extra = {"cascade_stage1": req.latency_s}
        if should_escalate:
            row.update(fields)
            escalate.append((row, req, rid, code))
        else:
            row.update(ok=True, prob=prob1, **fields)
            done.append((row, req, rid, fields, extra))
    for row, req, rid, fields, extra in done:
        service.finish_request(
            rid, 200, req.latency_s, req=req,
            extra_stages=extra, log_fields=fields,
        )
    if escalate:
        results = service.cascade.escalate_many(
            [code for _, _, _, code in escalate],
        )
        for (row, req, rid, _), (prob2, s2) in zip(escalate, results):
            extra = {"cascade_stage1": req.latency_s}
            if prob2 is None:
                # a failed stage-2 pass degrades to the stage-1 score —
                # never a failed request (the screen already answered)
                row.update(ok=True, prob=row["stage1_prob"])
                fields = {k: row[k] for k in (
                    "stage", "stage1_prob", "calibrated_prob")}
                fields["cascade_failed"] = 1
            else:
                row.update(ok=True, prob=prob2, stage=2)
                fields = {k: row[k] for k in (
                    "stage", "stage1_prob", "calibrated_prob")}
                extra["cascade_stage2"] = s2
            service.finish_request(
                rid, 200, req.latency_s, req=req,
                extra_stages=extra, log_fields=fields,
            )
    return rows


class UnknownModel(ValueError):
    """The request named a co-served model this process doesn't hold."""


class _Handler(BaseHTTPRequestHandler):
    service: ScoringService = None  # set by make_server
    request_timeout_s: float = 60.0

    def _service_for(self, payload: dict) -> "ScoringService":
        """Which service scores this request. The single-process server
        has exactly one; the fleet replica handler overrides this to
        route by the payload's `model` tag (multi-model co-serving,
        docs/fleet.md). Raises UnknownModel -> 400."""
        return self.service

    def _reply(self, status: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _reply_text(self, status: int, text: str) -> None:
        body = text.encode()
        self.send_response(status)
        self.send_header(
            "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
        )
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # route through logging, not stderr
        logger.debug("http: " + fmt, *args)

    def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
        url = urllib.parse.urlsplit(self.path)
        query = urllib.parse.parse_qs(url.query)
        if url.path == "/healthz":
            deep = query.get("deep", ["0"])[0] not in ("", "0", "false")
            self._reply(200, self.service.healthz(deep=deep))
        elif url.path == "/stats":
            self._reply(200, self.service.stats())
        elif url.path == "/metrics":
            self._reply_text(200, self.service.metrics_text())
        else:
            self._reply(404, {"error": f"no route {self.path}"})

    def do_POST(self):  # noqa: N802
        if self.path != "/score":
            self._reply(404, {"error": f"no route {self.path}"})
            return
        # an upstream router (deepdfa_tpu/fleet/) propagates the ingress
        # id so one request's flow chain spans router -> replica spans
        rid = self.headers.get("X-Request-Id") or new_request_id()
        t0 = time.monotonic()
        service = self.service
        try:
            n = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(n) or b"{}")
            if not isinstance(payload, dict):
                raise ValueError(
                    f"body must be a JSON object, got "
                    f"{type(payload).__name__}"
                )
            service = self._service_for(payload)
            code = payload["code"]
        except (ValueError, KeyError) as e:
            service.finish_request(rid, 400, time.monotonic() - t0)
            self._reply(
                400, {"error": f"bad request: {e}", "request_id": rid}
            )
            return
        want_trace = bool(payload.get("trace"))
        want_lines = bool(payload.get("lines"))
        if want_lines and service.localizer is None:
            # refused up front, before any device work: the contract is
            # explicit opt-in at server start (serve.lines=true warms
            # the attribution ladder), not a silent slow path
            service.finish_request(rid, 400, time.monotonic() - t0)
            self._reply(400, {
                "error": "line attributions are disabled on this server "
                         "(start it with serve.lines=true)",
                "request_id": rid,
            })
            return
        req = None
        feats = None
        cascade_fields: dict = {}
        cascade_extra: dict | None = None
        try:
            if want_lines:
                req, feats = service.submit_code(
                    code, request_id=rid, want_feats=True
                )
            else:
                req = service.submit_code(code, request_id=rid)
            prob = req.wait(self.request_timeout_s)
            if service.cascade is not None:
                # the cascade verdict (docs/cascade.md): screen on the
                # stage-1 prob, escalate the uncertain band through the
                # stage-2 batcher (handler threads co-batch there)
                prob, cascade_fields, cascade_extra = (
                    service.cascade_decide(code, prob, rid, req=req)
                )
            lines = (
                service.attribute_lines(feats, request_id=rid)
                if want_lines else None
            )
        except QueueFull as e:
            status, err = 429, e
        except RequestTooLarge as e:
            status, err = 413, e
        except FrontendError as e:
            status, err = 422, e
        except TimeoutError as e:
            status, err = 504, e
        except Exception as e:  # noqa: BLE001 - the any-status contract:
            # an executor failure (batcher does set_error(e), wait()
            # re-raises) must still be SLO-ingested and request-logged
            # as a 500, never escape as a dropped connection
            logger.exception("request %s failed", rid)
            status, err = 500, e
        else:
            stages = service.finish_request(
                rid, 200, time.monotonic() - t0, req=req,
                extra_stages=cascade_extra,
                log_fields=cascade_fields or None,
            )
            out = {
                "ok": True,
                "prob": prob,
                "latency_ms": round((time.monotonic() - t0) * 1e3, 3),
                "request_id": rid,
                **cascade_fields,
            }
            if lines is not None:
                out["lines"] = lines
            if want_trace:
                # opt-in per-request stage echo (docs/slo.md): where
                # this request's time went, straight off the request
                out["stages"] = stages
                out["batch_size"] = req.batch_size
            self._reply(200, out)
            return
        service.finish_request(
            rid, status, time.monotonic() - t0, req=req,
            frontend_s=getattr(err, "frontend_s", None),
        )
        self._reply(status, {"error": str(err), "request_id": rid})


def make_server(
    service: ScoringService, host: str = "127.0.0.1", port: int = 0
) -> ThreadingHTTPServer:
    """Bound (not yet serving) HTTP server; port 0 picks an ephemeral
    port (server.server_address[1] has the real one)."""
    handler = type("BoundHandler", (_Handler,), {"service": service})
    return ThreadingHTTPServer((host, port), handler)


def serve_forever(service: ScoringService, host: str, port: int) -> None:
    service.start()
    httpd = make_server(service, host, port)
    real_port = httpd.server_address[1]
    print(
        json.dumps({
            "serving": True, "host": host, "port": real_port,
            **service.healthz(),
        }),
        flush=True,
    )
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.server_close()
        service.close()


class BackgroundServer:
    """In-process server on an ephemeral port (smoke mode + tests)."""

    def __init__(self, service: ScoringService, host: str = "127.0.0.1"):
        self.service = service
        service.start()
        self.httpd = make_server(service, host, 0)
        self.host = host
        self.port = self.httpd.server_address[1]
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )
        self._thread.start()

    def request(self, method: str, path: str, payload: dict | None = None):
        status, raw = self.request_text(method, path, payload)
        return status, json.loads(raw or "{}")

    def request_text(
        self, method: str, path: str, payload: dict | None = None
    ):
        """(status, body-text) — for non-JSON routes like /metrics."""
        import http.client

        conn = http.client.HTTPConnection(self.host, self.port, timeout=60)
        body = json.dumps(payload) if payload is not None else None
        conn.request(
            method, path, body=body,
            headers={"Content-Type": "application/json"} if body else {},
        )
        resp = conn.getresponse()
        data = resp.read().decode("utf-8", "replace")
        conn.close()
        return resp.status, data

    def close(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        self._thread.join(timeout=10)
        self.service.close()
