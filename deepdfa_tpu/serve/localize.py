"""Served line-level localization: AOT per-node attribution executables
for the flagship GGNN family (docs/scanning.md).

`eval/localize.py:ggnn_score_fn` is the one attribution program — the
offline eval jits it directly; this module lowers THE SAME function
ahead of time for every size in the scoring executor's warmup ladder
(serve/batcher.py `_pow2_sizes`), so the line path inherits the
zero-steady-state-recompiles contract the score path already carries:
after `warmup()`, no request mix ever triggers a lowering
(`jit_lowerings()` is the guard, same convention as `GgnnExecutor`).

Numerics contract (tests/test_scan.py): a function attributed alone
through a warmed executable is BIT-IDENTICAL to the offline eval on the
same checkpoint (same program, same shapes). Co-batching preserves the
line RANKING and pins scores to float32 reduction tolerance — the
backward pass reassociates reductions across padded shapes, so the
forward score path's exact co-batching invariance does not extend to
gradients (docs/scanning.md).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Callable, Sequence

import numpy as np

from deepdfa_tpu.obs import (
    ledger as obs_ledger,
    metrics as obs_metrics,
    trace as obs_trace,
)
from deepdfa_tpu.serve.batcher import DeviceWindow, _donate_batch_argnums
from deepdfa_tpu.serve.frontend import Features


class GgnnLocalizer:
    """Signature-keyed AOT executables computing (probs, node scores)
    for padded graph batches, plus the host-side mapping from node
    scores back to ranked source lines."""

    def __init__(
        self,
        model,
        params_fn: Callable[[], Any],
        node_budget: int,
        edge_budget: int,
        sizes: Sequence[int],
        method: str = "saliency",
        n_steps: int = 8,
        top_k: int = 10,
        feat_width: int | None = None,
        etypes: bool = False,
        params_transform: Callable[[Any], Any] | None = None,
        mesh=None,
        pipeline_depth: int = 0,
    ):
        import jax

        from deepdfa_tpu.eval.localize import ggnn_score_fn

        # serve mesh (parallel/sharding.py): batches replicate, params
        # arrive registry-committed under the sharding map — same
        # contract as the scoring executor
        self.mesh = mesh
        self._batch_sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            self._batch_sharding = NamedSharding(mesh, PartitionSpec())
        self.model = model
        self.params_fn = params_fn
        self.node_budget = int(node_budget)
        self.edge_budget = int(edge_budget)
        #: the scoring executor's ladder — shared so score and line
        #: paths warm the same batch signatures
        self.sizes = tuple(sorted(set(int(s) for s in sizes)))
        self.method = method
        self.n_steps = int(n_steps)
        self.top_k = int(top_k)
        self.etypes = bool(etypes)
        if feat_width is None:
            from deepdfa_tpu.graphs.batch import NUM_SUBKEY_FEATS

            feat_width = NUM_SUBKEY_FEATS
        self.feat_width = int(feat_width)
        score_fn = ggnn_score_fn(method, model, n_steps)
        if params_transform is not None:
            # quantized entries (serve/quant.py): dequantize in-program,
            # same contract as the scoring executables
            base_fn = score_fn

            def score_fn(params, batch):  # noqa: F811 - deliberate wrap
                return base_fn(params_transform(params), batch)

        # the padded input batch is donated on accelerator backends —
        # same HBM double-buffering fix as the scoring ladder
        self._fn_jit = jax.jit(
            score_fn, donate_argnums=_donate_batch_argnums()
        )
        self._compiled: dict[int, Any] = {}
        self._lowerings = 0
        #: bounded in-flight window for the software-pipelined
        #: `attribute_all` drive; 0 = serial (docs/serving.md)
        self.pipeline_depth = max(0, int(pipeline_depth))
        #: FIFO-union dispatch->sync attribution (serve/batcher.py) —
        #: feeds the ledger's rolling-MFU join for the localize tag
        self._window = DeviceWindow()
        r = obs_metrics.REGISTRY
        self._m_requests = r.counter("localize/requests")
        self._m_batches = r.counter("localize/batches")
        self._m_seconds = r.histogram("localize/seconds")

    # -- compilation (the GgnnExecutor warmup contract) -----------------------

    def _place(self, batch):
        import jax

        if self._batch_sharding is not None:
            return jax.device_put(batch, self._batch_sharding)
        return jax.device_put(batch)

    def _dummy_batch(self, size: int):
        from deepdfa_tpu.graphs.batch import pack

        return pack(
            [], size, self.node_budget, self.edge_budget,
            feat_width=self.feat_width, etypes=self.etypes,
        )

    def signatures(self) -> list[tuple]:
        return [
            (s, self.node_budget, self.edge_budget) for s in self.sizes
        ]

    def warmup(self) -> dict[str, float]:
        """AOT-compile the attribution program at every ladder size;
        {signature label: seconds}. Idempotent."""
        import jax

        params = self.params_fn()
        report: dict[str, float] = {}
        for size in self.sizes:
            if size in self._compiled:
                continue
            t0 = time.perf_counter()
            batch = self._place(self._dummy_batch(size))
            self._compiled[size] = self._fn_jit.lower(
                params, batch
            ).compile()
            dt = time.perf_counter() - t0
            self._lowerings += 1
            obs_metrics.REGISTRY.counter("localize/compiles").inc()
            obs_ledger.record_compile(
                "localize", f"L{size}", self._compiled[size], dt
            )
            report[f"L{size}"] = round(dt, 3)
        obs_ledger.record_memory("warmup")
        return report

    def jit_lowerings(self) -> int:
        return self._lowerings + self._fn_jit._cache_size()

    # -- execution ------------------------------------------------------------

    def _size_for(self, n: int) -> int:
        for s in self.sizes:
            if s >= n:
                return s
        return self.sizes[-1]

    def fits(self, chunk: Sequence[Features], feats: Features) -> bool:
        """Would adding `feats` keep the chunk inside the pack budgets
        (same accounting as the scoring executor)?"""
        if len(chunk) + 1 > self.sizes[-1]:
            return False
        nodes = sum(f.spec.num_nodes for f in chunk) + feats.spec.num_nodes
        edges = (
            sum(f.spec.num_edges + f.spec.num_nodes for f in chunk)
            + feats.spec.num_edges + feats.spec.num_nodes
        )
        return nodes <= self.node_budget and edges <= self.edge_budget

    def _pack_chunk(self, feats_list: Sequence[Features]):
        """Host pack stage: (ladder size, padded batch)."""
        from deepdfa_tpu.graphs.batch import pack

        size = self._size_for(len(feats_list))
        batch = pack(
            [f.spec for f in feats_list], size,
            self.node_budget, self.edge_budget,
            feat_width=self.feat_width, etypes=self.etypes,
        )
        return size, batch

    def _dispatch(self, size: int, batch):
        """Place + submit WITHOUT syncing; returns the un-synced device
        (probs, node_scores) handle."""
        batch = self._place(batch)
        fn = self._compiled.get(size, self._fn_jit)
        return fn(self.params_fn(), batch)

    def _fetch(self, handle):
        """Sync point: pull (probs, node_scores) to host."""
        import jax

        probs, node_scores = handle
        return (
            np.asarray(jax.device_get(probs)),
            np.asarray(jax.device_get(node_scores)),
        )

    def _finish(
        self,
        feats_list: Sequence[Features],
        size: int,
        probs: np.ndarray,
        node_scores: np.ndarray,
        t_submit: float,
        t_sync: float,
    ) -> list[tuple[float, list[dict]]]:
        """Fetch-side epilogue: the ledger's measured execution window
        (FIFO-union dispatch->sync busy share — host pack and the line
        mapping below are EXCLUDED, matching the serve batcher's window
        semantics) plus the host node->line mapping."""
        from deepdfa_tpu.eval.localize import node_line_attributions

        busy = self._window.observe(t_submit, t_sync)
        obs_ledger.observe_execution("localize", f"L{size}", busy)
        out: list[tuple[float, list[dict]]] = []
        off = 0
        for i, f in enumerate(feats_list):
            n = f.spec.num_nodes
            out.append((
                float(probs[i]),
                node_line_attributions(
                    node_scores[off:off + n], f.node_lines,
                    top_k=self.top_k,
                ),
            ))
            off += n
        self._m_requests.inc(len(feats_list))
        self._m_batches.inc()
        return out

    def attribute(
        self, feats_list: Sequence[Features]
    ) -> list[tuple[float, list[dict]]]:
        """One padded executable over the chunk -> per-function
        (prob, ranked [{"line", "score"}]) in the function's OWN line
        coordinates. The chunk must respect the pack budgets (`fits`)."""
        if not feats_list:
            return []
        t0 = time.perf_counter()
        size, batch = self._pack_chunk(feats_list)
        with obs_trace.span(
            "localize_execute", cat="serve", signature=str(size),
            batch_size=len(feats_list),
        ):
            t_submit = time.perf_counter()
            handle = self._dispatch(size, batch)
            probs, node_scores = self._fetch(handle)
            t_sync = time.perf_counter()
        out = self._finish(
            feats_list, size, probs, node_scores, t_submit, t_sync
        )
        self._m_seconds.observe(time.perf_counter() - t0)
        return out

    def attribute_all(
        self, feats_list: Sequence[Features]
    ) -> list[tuple[float, list[dict]]]:
        """Greedy budget-respecting chunking over a function stream —
        the scan drive. Order preserved.

        With `pipeline_depth > 0` the drive is software-pipelined
        (docs/serving.md "Pipelined execution"): JAX dispatch is async,
        so packing + submitting the next chunk overlaps the device
        running the current one, with at most `pipeline_depth`
        dispatched-but-unsynced chunks behind the FIFO fetch. Chunking
        and per-chunk programs are identical to the serial drive, so the
        outputs are bit-identical."""
        chunks: list[list[Features]] = []
        chunk: list[Features] = []
        for f in feats_list:
            if chunk and not self.fits(chunk, f):
                chunks.append(chunk)
                chunk = []
            chunk.append(f)
        if chunk:
            chunks.append(chunk)
        if self.pipeline_depth <= 0:
            out: list[tuple[float, list[dict]]] = []
            for c in chunks:
                out.extend(self.attribute(c))
            return out
        out = []
        window: deque = deque()

        def _sync_oldest() -> None:
            c, size, handle, t_submit = window.popleft()
            with obs_trace.span(
                "localize_fetch", cat="serve", signature=str(size),
                batch_size=len(c),
            ):
                probs, node_scores = self._fetch(handle)
                t_sync = time.perf_counter()
            out.extend(
                self._finish(c, size, probs, node_scores, t_submit, t_sync)
            )

        for c in chunks:
            while len(window) >= self.pipeline_depth:
                _sync_oldest()
            t0 = time.perf_counter()
            size, batch = self._pack_chunk(c)
            with obs_trace.span(
                "localize_dispatch", cat="serve", signature=str(size),
                batch_size=len(c),
            ):
                t_submit = time.perf_counter()
                handle = self._dispatch(size, batch)
            window.append((c, size, handle, t_submit))
            self._m_seconds.observe(time.perf_counter() - t0)
        while window:
            _sync_oldest()
        return out
