"""Offline scoring drives + the self-contained serve smoke run.

`run_score` is the `deepdfa-tpu score` implementation: restore a run's
checkpoint through the registry, push a directory of C functions through
the online path (frontend -> batcher -> AOT executables), write per-file
scores JSONL + a serve metrics record, and report the summary the
benches and tests assert on (throughput, latency quantiles, batch
occupancy, steady-state recompiles).

`build_smoke_run` trains a tiny GGNN on the synthetic corpus and lays
down EXACTLY the artifacts a real run leaves (config.json, checkpoints/
with a `best` tag, the feat-spec-named vocab json, a directory of source
files) — so `score --smoke` / `serve --smoke` and the schema checker
exercise the real restore path end to end, not a mock.
"""

from __future__ import annotations

import json
import time
from pathlib import Path

#: source extensions the `score` command collects from a directory
SOURCE_SUFFIXES = (".c", ".cc", ".cpp", ".cxx", ".h", ".hpp")


def collect_sources(paths_in: list[str]) -> list[tuple[str, str]]:
    """(name, code) pairs from files and/or directories of C sources."""
    out: list[tuple[str, str]] = []
    for p in paths_in:
        p = Path(p)
        if p.is_dir():
            for f in sorted(p.rglob("*")):
                if f.suffix in SOURCE_SUFFIXES and f.is_file():
                    out.append((str(f), f.read_text(errors="replace")))
        elif p.is_file():
            out.append((str(p), p.read_text(errors="replace")))
        else:
            raise SystemExit(f"no such source file/dir: {p}")
    if not out:
        raise SystemExit(
            f"no source files found under {paths_in} "
            f"(looked for {SOURCE_SUFFIXES})"
        )
    return out


def build_smoke_run(
    run_name: str = "serve-smoke",
    dataset: str = "serve-smoke",
    n_examples: int = 24,
    max_epochs: int = 2,
    seed: int = 0,
    extra_overrides: list[str] | None = None,
    vuln_rate: float = 0.06,
):
    """Train a tiny GGNN and leave real run artifacts behind.

    Returns (cfg, run_dir, sources_dir). Must run on a 1-device CPU
    platform (CLI subprocesses; see tests/conftest.py:run_cli)."""
    import numpy as np

    from deepdfa_tpu.core import Config, config as config_mod, paths
    from deepdfa_tpu.data import build_dataset, generate, to_examples
    from deepdfa_tpu.graphs import shard_bucket_batches
    from deepdfa_tpu.models import DeepDFA
    from deepdfa_tpu.train import GraphTrainer

    cfg = config_mod.apply_overrides(Config(), [
        f"run_name={json.dumps(run_name)}",
        f"data.dataset={json.dumps(dataset)}",
        'data.feat={"limit_all": 50, "limit_subkeys": 50}',
        f"train.max_epochs={max_epochs}",
        "model.hidden_dim=8", "model.n_steps=2",
        # small serve batches keep the AOT ladder cheap to warm on CPU
        "serve.max_batch_graphs=4",
        "serve.node_budget=2048", "serve.edge_budget=8192",
        # smokes exercise the pipelined path end-to-end (depth=2); the
        # production default stays 0 = serial (core/config.py)
        "serve.pipeline_depth=2",
        *(extra_overrides or []),
    ])
    # vuln_rate: the dataset's ~6% positive rate by default; the cascade
    # bench asks for a balanced dev set (AUC over 3 positives is noise)
    synth = generate(n_examples, vuln_rate=vuln_rate, seed=seed)
    examples = to_examples(synth)
    specs, vocabs = build_dataset(
        examples, train_ids=range(n_examples),
        limit_all=cfg.data.feat.limit_all,
        limit_subkeys=cfg.data.feat.limit_subkeys,
    )
    out_dir = paths.processed_dir(dataset)
    (out_dir / f"vocab{cfg.data.feat.name}.json").write_text(
        json.dumps({k: v.to_json() for k, v in vocabs.items()})
    )
    run_dir = paths.runs_dir(run_name)
    config_mod.to_json(cfg, run_dir / "config.json")

    model = DeepDFA.from_config(
        cfg.model, input_dim=cfg.data.feat.input_dim
    )
    trainer = GraphTrainer(model, cfg)

    def batches(_e=0):
        return shard_bucket_batches(
            specs, 1, 8, 2048, 8192, oversized="raise"
        )

    state = trainer.init_state(next(iter(batches())))
    ckpts = trainer.make_checkpoints(run_dir / "checkpoints")
    trainer.fit(
        state, batches, val_batches=batches, checkpoints=ckpts,
    )

    sources_dir = run_dir / "smoke_src"
    sources_dir.mkdir(parents=True, exist_ok=True)
    for e in examples:
        (sources_dir / f"fn_{e.id:04d}.c").write_text(e.code)
    return cfg, run_dir, sources_dir


def run_score(
    cfg,
    run_dir,
    sources: list[tuple[str, str]],
    out_path=None,
    family: str = "deepdfa",
) -> dict:
    """Score (name, code) pairs against a run's checkpoint; returns the
    summary record (also appended to <run_dir>/serve_log.jsonl)."""
    from deepdfa_tpu.obs import metrics as obs_metrics
    from deepdfa_tpu.serve.registry import ModelRegistry
    from deepdfa_tpu.serve.server import (
        ScoringService,
        score_texts,
        write_serve_log,
    )

    run_dir = Path(run_dir)
    from deepdfa_tpu.serve.registry import serve_mesh

    registry = ModelRegistry(
        run_dir, family=family, checkpoint=cfg.serve.checkpoint, cfg=cfg,
        mesh=serve_mesh(cfg),
    )
    service = ScoringService(registry, cfg)
    try:
        t0 = time.perf_counter()
        rows = score_texts(service, sources)
        dt = time.perf_counter() - t0
        out_path = (
            Path(out_path) if out_path else run_dir / "scores.jsonl"
        )
        with out_path.open("w") as f:
            for row in rows:
                f.write(json.dumps(row) + "\n")
        from deepdfa_tpu.serve.batcher import percentile

        ok = sum(1 for r in rows if r.get("ok"))
        lat = sorted(service.batcher.recent_latencies)

        def pct_ms(p):
            v = percentile(lat, p)
            return None if v is None else round(1e3 * v, 3)

        snap = obs_metrics.REGISTRY.snapshot()
        summary = {
            "serve_scored": ok,
            "serve_failed_requests": len(rows) - ok,
            "serve_seconds": round(dt, 3),
            "serve_requests_per_sec": round(ok / dt, 2) if dt else None,
            "serve_latency_p50_ms": pct_ms(0.50),
            "serve_latency_p99_ms": pct_ms(0.99),
            "serve_batch_occupancy_mean": round(
                snap.get("serve/batch_occupancy/mean", 0.0), 4
            ),
            "serve_jit_lowerings": service.executor.jit_lowerings(),
            "serve_steady_state_recompiles": (
                service.steady_state_recompiles()
            ),
            "scores_path": str(out_path),
        }
        record = dict(summary)
        record.update(service.serve_record())
        write_serve_log(run_dir, [record])
        return summary
    finally:
        service.close()


def run_serve_smoke(extra_overrides=None, **smoke_kw) -> dict:
    """`serve --smoke`: smoke run + real HTTP round trips on an
    ephemeral port, then teardown. Beyond the PR-5 contract (score 200s,
    a 422 reject, healthz/stats, zero steady-state recompiles) the smoke
    now exercises the full observability surface (ISSUE 6 acceptance):
    request tracing is ON (the merged trace must flow-link one request's
    frontend/queue/device spans under its request_id), `/metrics` is
    scraped to <run_dir>/metrics.prom for schema validation, a deep
    healthz probes the backend, and per-request entries land in
    serve_log.jsonl for the diag SLO section."""
    from deepdfa_tpu import obs
    from deepdfa_tpu.obs import (
        flight as obs_flight,
        ledger as obs_ledger,
        trace as obs_trace,
    )
    from deepdfa_tpu.serve import cascade as cascade_mod
    from deepdfa_tpu.serve.registry import ModelRegistry
    from deepdfa_tpu.serve.server import (
        BackgroundServer,
        ScoringService,
        write_serve_log,
    )

    cfg, run_dir, sources_dir = build_smoke_run(
        extra_overrides=[
            "serve.request_log=true",
            "obs.trace=true",
            # the cascade rides the smoke (docs/cascade.md): stage-2
            # combined artifacts are laid down below, /score escalates
            # the uncertain band, and the smoke asserts per-stage SLO
            # fields + zero recompiles across BOTH family ladders
            "serve.cascade=true",
            # tiny stage-2 serve batches (rows_for_bucket(32, 128) = 4)
            "data.token_budget=128",
            # the efficiency ledger + flight recorder ride the smoke
            # (docs/efficiency.md): every warmup compile is cost-
            # accounted, /metrics carries ledger/* families, and a
            # validation dump proves the postmortem path end to end
            "obs.ledger=true",
            "obs.flight=true",
            # line-level localization rides the smoke too (ISSUE 8):
            # the attribution ladder AOT-warms next to the score ladder
            # and one request opts into {"lines": true}
            "serve.lines=true",
            "serve.lines_steps=2",
            # caller overrides last so `serve --smoke --override ...`
            # can flip any knob (e.g. model.ggnn_kernel) end to end
            *(extra_overrides or []),
        ],
        **smoke_kw,
    )
    # stage-2 artifacts (checkpoints-combined/ + model_cfg.json) before
    # the cascade service restores them
    cascade_mod.build_stage2_smoke(run_dir, cfg, family="combined")
    with obs.session(cfg, run_dir):
        registry = ModelRegistry(
            run_dir, family="deepdfa", checkpoint=cfg.serve.checkpoint,
            cfg=cfg,
        )
        service = ScoringService(registry, cfg)
        server = BackgroundServer(service)
        try:
            codes = [
                f.read_text() for f in sorted(sources_dir.glob("*.c"))[:6]
            ]
            scored = []
            line_attrs = None
            for i, code in enumerate(codes):
                # the first request opts into the per-stage trace echo,
                # the second into served line attributions
                payload: dict = {"code": code}
                if i == 0:
                    payload["trace"] = True
                elif i == 1:
                    payload["lines"] = True
                status, resp = server.request("POST", "/score", payload)
                if i == 1:
                    line_attrs = resp.get("lines")
                scored.append(
                    (status, resp.get("prob"), resp.get("request_id"),
                     resp.get("stages"), resp.get("stage"),
                     resp.get("stage1_prob"))
                )
            bad_status, _ = server.request(
                "POST", "/score", {"code": "not a function @@@"}
            )
            h_status, health = server.request("GET", "/healthz")
            dh_status, deep_health = server.request(
                "GET", "/healthz?deep=1"
            )
            s_status, stats = server.request("GET", "/stats")
            m_status, metrics_text = server.request_text(
                "GET", "/metrics"
            )
            (run_dir / "metrics.prom").write_text(metrics_text)
            record = dict(service.serve_record())
            record["serve_steady_state_recompiles"] = (
                service.steady_state_recompiles()
            )
            write_serve_log(run_dir, [record])
            # cascade evidence (ISSUE 12): which stage decided each
            # request, escalation accounting consistent with the
            # responses, per-stage SLO windows populated, and the
            # cascade-mode serve_log schema-valid
            cascade_report = None
            if service.cascade is not None:
                counters = service.cascade.counters()
                stages_seen = [s for _, _, _, _, s, _ in scored]
                slo_snap = service.slo.snapshot()
                stage1_windowed = any(
                    "cascade_stage1" in (v.get("latency_ms") or {})
                    for v in slo_snap.values() if isinstance(v, dict)
                )
                cascade_report = {
                    "stages": stages_seen,
                    "stage_fields_present": all(
                        s in (1, 2) and p1 is not None
                        for st, _, _, _, s, p1 in scored if st == 200
                    ),
                    "escalations_consistent": (
                        counters["escalations"]
                        == sum(1 for s in stages_seen if s == 2)
                    ),
                    "counters": counters,
                    "band": list(service.cascade.band),
                    "stage1_windowed": stage1_windowed,
                    "stage2_steady_state_recompiles": (
                        service.cascade.service.steady_state_recompiles()
                    ),
                }
            ledger_snap = obs_ledger.snapshot_or_none() or {}
            # the flight-recorder validation dump: a real postmortem
            # written by the serving process (with its warmup ledger
            # and request history on board), validated below by the
            # same checker `check_obs_schema.py --postmortem` runs
            postmortem_path = obs_flight.crash_dump(
                "smoke_test", extra={"reason": "serve-smoke validation"}
            )
        finally:
            server.close()
    postmortem = (
        obs_flight.validate_postmortem_file(postmortem_path)
        if postmortem_path is not None
        else {"ok": False, "problems": ["no postmortem dumped"]}
    )
    # the session is closed: per-process trace files are flushed and the
    # merged trace.json is written — verify one scored request's spans
    # are flow-linked under its request_id (the acceptance criterion)
    rid = next((r for _, _, r, _, _, _ in scored if r), None)
    events = obs_trace.merge(run_dir / "trace")
    flow_phases = sorted({
        e["ph"] for e in events
        if e.get("id") == rid and e.get("ph") in ("s", "t", "f")
    })
    linked_spans = set()
    for e in events:
        if e.get("ph") != "X":
            continue
        args = e.get("args") or {}
        if (
            args.get("request_id") == rid
            or rid in (args.get("request_ids") or [])
        ):
            linked_spans.add(e["name"])
    linked_spans = sorted(linked_spans)
    if cascade_report is not None:
        cascade_report["log"] = cascade_mod.validate_cascade_log(
            run_dir / "serve_log.jsonl"
        )
        cascade_report["ok"] = bool(
            cascade_report["stage_fields_present"]
            and cascade_report["escalations_consistent"]
            and cascade_report["stage1_windowed"]
            and cascade_report["stage2_steady_state_recompiles"] == 0
            and cascade_report["log"]["ok"]
        )
    return {
        "scored": [
            {"status": st, "prob": p, "request_id": r,
             **({"stages": stg} if stg else {}),
             **({"stage": s} if s is not None else {}),
             **({"stage1_prob": p1} if p1 is not None else {})}
            for st, p, r, stg, s, p1 in scored
        ],
        "cascade": cascade_report,
        "line_attributions": line_attrs,
        "reject_status": bad_status,
        "healthz_status": h_status,
        "healthz": health,
        "deep_healthz_status": dh_status,
        "deep_healthz_backend": deep_health.get("backend"),
        "stats_status": s_status,
        "stats": stats,
        "metrics_status": m_status,
        "metrics_path": str(run_dir / "metrics.prom"),
        "trace_flow_phases": flow_phases,
        "trace_linked_spans": linked_spans,
        # device efficiency + forensics (docs/efficiency.md): the smoke
        # asserts warmup compiles were cost-accounted and the dumped
        # postmortem is schema-valid
        "ledger_sites": sorted((ledger_snap.get("sites") or {})),
        "ledger_compile_seconds_total": ledger_snap.get(
            "compile_seconds_total"
        ),
        "postmortem": postmortem,
        "steady_state_recompiles": (
            service.steady_state_recompiles()
        ),
        "run_dir": str(run_dir),
    }
