"""Post-training int8 quantization for serving executables
(docs/cascade.md).

The paper's pitch is cheap inference; this module makes the *weights*
cheap too. A registry checkpoint tag with the `@int8` suffix
(`serve.checkpoint=best@int8`, or a fleet co-serving entry's checkpoint
field) restores the fp32 params and rewrites the pytree:

- **matmul/einsum weights** (float leaves with ndim >= 2 — kernels,
  embeddings, attention projections) become per-channel SYMMETRIC int8:
  one fp32 scale per output channel (last axis), values rounded into
  [-127, 127]. Symmetric means dequant is a single multiply — no zero
  point — which XLA fuses straight into the consumer matmul.
- **everything else float** (biases, norms, GRU gate vectors) becomes
  bfloat16 — the PR-8 message-policy precedent: cheap to store, f32 on
  use.
- non-float leaves (none today) pass through untouched.

Execution stays f32-accumulated: the quantized tree is what lives in
HBM and what the AOT executables take as their params argument (the
HBM-density win the per-entry param-bytes ledger measures); the
executors run `dequantize_params` INSIDE the jitted program, so the
convert+scale is compile-time-fused and the math after it is the same
fp32 graph the plain entry runs.

The drift contract: quantization is admitted at registry load only if
the max probability drift vs the fp32 params over a deterministic
calibration batch set stays within `serve.quant_drift_bound` (default
5e-2). An over-bound quantization is refused LOUDLY — the error names
the param paths with the worst quantization error, CheckpointMismatch
style — because silently serving a degraded model is the one failure
mode a density optimization must never have.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Mapping

import numpy as np

#: the registry tag suffix that requests quantized restore
QUANT_SUFFIX = "@int8"

#: quantized-leaf marker keys (a dict with exactly these keys is one
#: quantized weight; anything else is an ordinary pytree node)
_QKEYS = frozenset({"int8", "scale"})


class QuantizationError(RuntimeError):
    """Quantization refused: drift past the configured bound.

    Carries the measured drift, the bound, and the offending param paths
    (worst quantization error first) — the CheckpointMismatch-style loud
    refusal serve/registry.py re-raises as a RegistryError."""

    def __init__(self, drift: float, bound: float, worst_paths: list[str]):
        self.drift = float(drift)
        self.bound = float(bound)
        self.worst_paths = list(worst_paths)
        super().__init__(
            f"int8 quantization refused: calibration prob drift "
            f"{drift:.3e} exceeds serve.quant_drift_bound={bound:g}; "
            f"worst-quantized params: {', '.join(worst_paths[:8])}"
            + ("..." if len(worst_paths) > 8 else "")
            + " (raise the bound, or serve the fp32 entry)"
        )


def split_checkpoint_tag(tag: str) -> tuple[str, str | None]:
    """`"best@int8"` -> ("best", "int8"); plain tags -> (tag, None)."""
    if tag.endswith(QUANT_SUFFIX):
        return tag[: -len(QUANT_SUFFIX)], "int8"
    return tag, None


def _is_float(leaf) -> bool:
    try:
        return np.issubdtype(np.asarray(leaf).dtype, np.floating)
    except Exception:
        return False


def is_quantized_leaf(node: Any) -> bool:
    return isinstance(node, Mapping) and set(node.keys()) == set(_QKEYS)


def quantize_leaf(w: np.ndarray) -> dict:
    """One weight -> per-channel symmetric int8 over the LAST axis."""
    w = np.asarray(w, dtype=np.float32)
    absmax = np.max(np.abs(w), axis=tuple(range(w.ndim - 1)))
    scale = (absmax / 127.0).astype(np.float32)
    scale = np.where(scale > 0, scale, np.float32(1.0))
    q = np.clip(np.rint(w / scale), -127, 127).astype(np.int8)
    return {"int8": q, "scale": scale}


def quantize_params(params: Any) -> Any:
    """fp32 params pytree -> the int8/bf16 serving tree.

    Mappings are rebuilt as plain dicts (orbax restores produce them
    anyway, and flax `apply` accepts them), so the quantized tree is a
    uniform host-side structure `jax.device_put` ships as-is."""
    import jax.numpy as jnp

    def walk(node):
        if isinstance(node, Mapping):
            return {k: walk(v) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            return type(node)(walk(v) for v in node)
        if _is_float(node):
            arr = np.asarray(node)
            if arr.ndim >= 2:
                return quantize_leaf(arr)
            return jnp.asarray(arr, dtype=jnp.bfloat16)
        return node

    import jax

    return walk(jax.device_get(params))


def dequantize_params(qtree: Any) -> Any:
    """The serving tree -> f32 params, jit-traceable.

    Runs INSIDE the compiled program (the executors' `params_transform`
    hook): int8 weights dequantize with one fused multiply, bf16 leaves
    upcast, so accumulation stays f32 while HBM holds the small tree.
    Leaves may be tracers, so dtypes are read off the leaf attribute,
    never through numpy."""
    import jax.numpy as jnp

    def walk(node):
        if is_quantized_leaf(node):
            return node["int8"].astype(jnp.float32) * node["scale"]
        if isinstance(node, Mapping):
            return {k: walk(v) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            return type(node)(walk(v) for v in node)
        dt = getattr(node, "dtype", None)
        if (
            dt is not None
            and jnp.issubdtype(dt, jnp.floating)
            and dt != jnp.float32
        ):
            return node.astype(jnp.float32)
        return node

    return walk(qtree)


def tree_bytes(tree: Any) -> float:
    """Total leaf bytes of a (possibly quantized) pytree — the same
    accounting fleet/replica.py:param_bytes and the efficiency ledger
    use, so the density win reads identically everywhere."""
    import jax

    total = 0.0
    for leaf in jax.tree.leaves(tree):
        try:
            total += float(
                np.prod(np.asarray(leaf).shape)
                * np.asarray(leaf).dtype.itemsize
            )
        except Exception:
            continue
    return total


def _flat_paths(tree: Any) -> dict[str, Any]:
    """{'a/b/c': leaf} over an arbitrary nested structure (quantized
    marker dicts count as ONE leaf at their path)."""
    out: dict[str, Any] = {}

    def walk(node, prefix):
        if is_quantized_leaf(node):
            out[prefix.rstrip("/")] = node
        elif isinstance(node, Mapping):
            for k, v in node.items():
                walk(v, f"{prefix}{k}/")
        elif isinstance(node, (list, tuple)):
            for i, v in enumerate(node):
                walk(v, f"{prefix}{i}/")
        else:
            out[prefix.rstrip("/")] = node

    walk(tree, "")
    return out


@dataclasses.dataclass(frozen=True)
class QuantReport:
    """What quantization did to one params tree (the /healthz + refusal
    payload): byte totals and the per-path reconstruction error."""

    bytes_fp32: float
    bytes_quant: float
    path_errors: dict[str, float]  # path -> max |w - dequant(w)|

    @property
    def bytes_fraction(self) -> float:
        return self.bytes_quant / self.bytes_fp32 if self.bytes_fp32 else 1.0

    def worst_paths(self) -> list[str]:
        return [
            p for p, _ in sorted(
                self.path_errors.items(), key=lambda kv: -kv[1]
            )
        ]


def quant_report(params: Any, qtree: Any) -> QuantReport:
    import jax

    params = jax.device_get(params)
    want = _flat_paths(params)
    have = _flat_paths(qtree)
    errors: dict[str, float] = {}
    for path, node in have.items():
        if not is_quantized_leaf(node):
            continue
        w = np.asarray(want[path], dtype=np.float32)
        deq = (
            np.asarray(node["int8"], dtype=np.float32)
            * np.asarray(node["scale"], dtype=np.float32)
        )
        errors[path] = float(np.max(np.abs(w - deq))) if w.size else 0.0
    return QuantReport(
        bytes_fp32=tree_bytes(params),
        bytes_quant=tree_bytes(qtree),
        path_errors=errors,
    )


# ---------------------------------------------------------------------------
# calibration (the drift contract's measurement half)


def calibration_graph_batch(
    size: int,
    node_budget: int,
    edge_budget: int,
    feat_width: int,
    input_dim: int,
    etypes: bool = False,
    n_etypes: int = 1,
    seed: int = 0,
):
    """A deterministic random-feature packed GraphBatch at one warmup
    ladder signature — enough signal to expose weight-reconstruction
    error in every layer (an all-padding dummy batch would only exercise
    the bias paths)."""
    from deepdfa_tpu.graphs.batch import GraphSpec, pack

    rng = np.random.default_rng(seed)
    specs = []
    for g in range(size):
        n = int(rng.integers(4, 12))
        # a chain + a few random extra edges: connected, varied degrees
        src = list(range(n - 1)) + list(rng.integers(0, n, size=3))
        dst = list(range(1, n)) + list(rng.integers(0, n, size=3))
        specs.append(GraphSpec(
            graph_id=g,
            node_feats=rng.integers(
                0, input_dim, size=(n, feat_width)
            ).astype(np.int32),
            node_vuln=np.zeros(n, np.int32),
            edge_src=np.asarray(src, np.int32),
            edge_dst=np.asarray(dst, np.int32),
            label=float(g % 2),
            edge_type=(
                rng.integers(0, n_etypes, size=len(src)).astype(np.int32)
                if etypes else None
            ),
        ))
    return pack(
        specs, size, node_budget, edge_budget,
        feat_width=feat_width, etypes=etypes,
    )


def calibration_text_batch(
    rows: int,
    seq_len: int,
    vocab_size: int,
    pad_id: int,
    node_budget: int,
    edge_budget: int,
    seed: int = 0,
):
    """Deterministic random token rows collated with empty graph slots —
    the combined/t5 families' calibration input."""
    from deepdfa_tpu.data.text import collate

    rng = np.random.default_rng(seed)
    ids = rng.integers(4, vocab_size, size=(rows, seq_len)).astype(np.int32)
    # realistic ragged lengths: pad the tail of each row
    for r in range(rows):
        ln = int(rng.integers(max(4, seq_len // 4), seq_len + 1))
        ids[r, ln:] = pad_id
    return collate(
        ids, [0] * rows, list(range(rows)), {},
        batch_rows=rows, node_budget=node_budget,
        edge_budget=edge_budget, pad_id=pad_id,
    )


def max_prob_drift(
    score_fn: Callable[[Any, Any], np.ndarray],
    params_fp32: Any,
    qtree: Any,
    batches: list,
) -> float:
    """max |P_quant - P_fp32| over the calibration batches. `score_fn`
    takes (f32 params, batch) -> probs; the quantized side dequantizes
    first, exactly as the serving executables do."""
    import jax

    drift = 0.0
    for batch in batches:
        p_ref = np.asarray(jax.device_get(score_fn(params_fp32, batch)))
        p_q = np.asarray(jax.device_get(
            score_fn(dequantize_params(qtree), batch)
        ))
        if p_ref.size:
            drift = max(drift, float(np.max(np.abs(p_ref - p_q))))
    return drift


def check_drift(
    score_fn: Callable[[Any, Any], np.ndarray],
    params_fp32: Any,
    qtree: Any,
    batches: list,
    bound: float,
) -> float:
    """The admission check: returns the measured drift, or raises
    QuantizationError naming the worst-quantized param paths."""
    drift = max_prob_drift(score_fn, params_fp32, qtree, batches)
    if drift > float(bound):
        report = quant_report(params_fp32, qtree)
        raise QuantizationError(drift, bound, report.worst_paths())
    return drift
