"""Dynamic request batcher with AOT bucket executables (docs/serving.md).

Morphling-style serving economics (PAPERS.md, arxiv 2512.01678): GNN
serving throughput comes from executing signature-specialized compiled
programs, never from tracing at request time. This batcher reuses the
PR-2 machinery — a small ladder of static batch signatures, each
ahead-of-time compiled before the first request (`warmup`), with
`jit_lowerings()` as the zero-steady-state-recompiles guard — and adds
the online half:

  - a BOUNDED queue with admission control: a full queue rejects
    (`QueueFull` -> HTTP 429) instead of buffering unbounded latency;
  - grouping of pending requests by bucket signature (graphs group by
    packed-budget fit; text rows group by their PR-2 sequence bucket
    edge `(T, rows, num_graphs)`);
  - a max-latency flush timer: a partial batch executes once its oldest
    request has waited `max_batch_delay_ms`, so a lone request never
    waits for co-arrivals.

Correctness invariant (tests/test_serve.py property test): a request's
score is BIT-IDENTICAL regardless of which other requests it was batched
with — padding slots are masked out of every segment reduction and
per-row compute is independent, so co-batching is purely a throughput
decision, never a numerics one.
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Callable, Hashable, Sequence

import numpy as np

from deepdfa_tpu.obs import (
    flight as obs_flight,
    ledger as obs_ledger,
    metrics as obs_metrics,
    trace as obs_trace,
)
from deepdfa_tpu.obs.slo import percentile  # noqa: F401 - canonical rule,
# re-exported here because serve callers historically import it from the
# batcher (obs/slo.py owns it now so /metrics shares the convention)

_req_ids = itertools.count()


def new_request_id() -> str:
    """Process-unique request id assigned at ingress ("<pid hex>-<seq
    hex>") — the flow-event id that links one request's frontend, queue,
    and device spans in the merged trace, and the `request_id` echoed in
    `/score` responses and serve_log.jsonl entries (docs/slo.md)."""
    return f"{os.getpid():x}-{next(_req_ids):x}"


class QueueFull(RuntimeError):
    """Admission control: the bounded request queue is at queue_limit."""


class RequestTooLarge(ValueError):
    """The request alone exceeds the serving batch budgets."""


@dataclasses.dataclass
class ScoreRequest:
    """One in-flight scoring request (a thread-safe future).

    Besides the score future, the request carries its own stage
    attribution (filled in by the frontend caller and the batch runner):
    `frontend_s` extraction time, `queue_wait_s` time between submit and
    batch start, `device_s` the executed batch's device time,
    `batch_size` how many requests shared that batch — the fields the
    SLO engine ingests and the opt-in `/score` trace echo returns."""

    payload: Any
    id: int = dataclasses.field(default_factory=lambda: next(_req_ids))
    request_id: str = dataclasses.field(default_factory=new_request_id)
    t_submit: float = dataclasses.field(default_factory=time.monotonic)
    _done: threading.Event = dataclasses.field(
        default_factory=threading.Event
    )
    result: float | None = None
    error: Exception | None = None
    latency_s: float | None = None
    frontend_s: float | None = None
    queue_wait_s: float | None = None
    device_s: float | None = None
    batch_size: int | None = None

    def set_result(self, value: float) -> None:
        self.result = value
        self.latency_s = time.monotonic() - self.t_submit
        self._done.set()

    def set_error(self, exc: Exception) -> None:
        self.error = exc
        self.latency_s = time.monotonic() - self.t_submit
        self._done.set()

    def wait(self, timeout: float | None = None) -> float:
        if not self._done.wait(timeout):
            raise TimeoutError(f"request {self.id} not scored in {timeout}s")
        if self.error is not None:
            raise self.error
        return float(self.result)


def _pow2_sizes(max_size: int) -> tuple[int, ...]:
    """The AOT bucket ladder: 1, 2, 4, ..., max (max included even when
    not a power of two — it is the capacity the scheduler fills to)."""
    sizes = []
    s = 1
    while s < max_size:
        sizes.append(s)
        s *= 2
    sizes.append(max_size)
    return tuple(sorted(set(sizes)))


def _ladder_sizes(
    ladder: Sequence[int] | None, capacity: int
) -> tuple[int, ...]:
    """Resolve an executor's warmup-ladder rungs: the tuned rung set
    when one was provided (deepdfa_tpu/tune/, docs/tuning.md — clamped
    to capacity, capacity always present so every legal chunk fits a
    warmed rung), else the historical pow2 ladder."""
    capacity = int(capacity)
    if not ladder:
        return _pow2_sizes(capacity)
    rungs = sorted({int(s) for s in ladder if 1 <= int(s) <= capacity})
    if not rungs or rungs[-1] != capacity:
        rungs.append(capacity)
    return tuple(rungs)


def _donate_batch_argnums() -> tuple[int, ...]:
    """Donation spec for the compiled ladder programs: the placed batch
    argument is donated on accelerator backends, so the padded input
    stops double-buffering in HBM (XLA may alias its buffers for
    outputs/scratch — every batch is freshly packed and placed per
    execution, never reused after the call). CPU skips donation:
    jaxlib's CPU client cannot use these donated buffers and would warn
    on every lowering."""
    import jax

    return (1,) if jax.default_backend() != "cpu" else ()


class DeviceWindow:
    """FIFO union attribution of device-busy time over submit->sync
    windows that may OVERLAP under pipelining (docs/serving.md
    "Pipelined execution").

    With batches dispatched back to back, batch i's raw submit->sync
    window includes time spent queued behind batch i-1 on the device;
    summing raw windows would over-count device seconds (and therefore
    rolling MFU). Because fetches sync in FIFO order, the device-busy
    interval attributable to batch i is exactly
    `[max(submit_i, sync_{i-1}), sync_i]` — the union decomposition.
    At pipeline depth 0 (serial) `sync_{i-1} <= submit_i` always holds
    and the busy window degenerates to the plain submit->sync time, so
    ONE accounting serves both paths. The complementary gap
    `max(0, submit_i - sync_{i-1})` is device-idle time — the overlap
    gap the pipeline exists to close."""

    def __init__(self):
        self.last_sync: float | None = None
        self.busy_s = 0.0
        self.idle_s = 0.0

    def observe(self, t_submit: float, t_sync: float) -> float:
        """Fold one submit->sync window in; returns its busy share."""
        last = self.last_sync
        start = t_submit if last is None else max(t_submit, last)
        busy = max(0.0, t_sync - start)
        if last is not None:
            self.idle_s += max(0.0, t_submit - last)
        self.busy_s += busy
        self.last_sync = max(t_sync, last or t_sync)
        return busy

    def idle_fraction(self) -> float | None:
        total = self.busy_s + self.idle_s
        return (self.idle_s / total) if total > 0.0 else None


def _observe_ladder_fill(label: str, used: int, capacity: int) -> None:
    """The ladder blind-spot gauge (docs/tuning.md): per-rung real vs
    padded row counters plus the process-wide `serve/ladder_waste`
    fraction, emitted on EVERY executed batch so a request stream whose
    sizes all land just above a rung (padding ~2x forever) is visible
    even with tuning off."""
    r = obs_metrics.REGISTRY
    pad = max(0, int(capacity) - int(used))
    r.counter(f"serve/ladder/{label}/real_rows").inc(used)
    if pad:
        r.counter(f"serve/ladder/{label}/padded_rows").inc(pad)
    real_c = r.counter("serve/ladder_real_rows")
    pad_c = r.counter("serve/ladder_padded_rows")
    real_c.inc(used)
    pad_c.inc(pad)
    total = real_c.value + pad_c.value
    if total:
        r.gauge("serve/ladder_waste").set(pad_c.value / total)


class GgnnExecutor:
    """Per-signature AOT executables for the flagship GGNN scorer.

    Payloads are `GraphSpec`s (the serve frontend's output). One grouping
    key — every graph request is co-batchable — with capacity bounded by
    `max_batch_graphs` AND the packed node/edge budgets; each executed
    chunk pads to the smallest warmed ladder size >= its row count, so a
    partial flush runs a smaller compiled program instead of paying the
    full batch's padded compute.
    """

    def __init__(
        self,
        model,
        params_fn: Callable[[], Any],
        node_budget: int,
        edge_budget: int,
        max_batch_graphs: int = 16,
        feat_width: int | None = None,
        etypes: bool = False,
        params_transform: Callable[[Any], Any] | None = None,
        mesh=None,
        ladder: Sequence[int] | None = None,
    ):
        """mesh: an optional serve mesh (parallel/sharding.py,
        docs/sharding.md) — batches replicate over it and params arrive
        from `params_fn` already committed under the registry's resolved
        sharding map, so the AOT ladder compiles GSPMD-partitioned
        programs with the same signatures (zero-recompile contract
        unchanged). None = the historical single-device placement.

        ladder: explicit warmup rungs replacing the pow2 default — the
        tuned layout (deepdfa_tpu/tune/, docs/tuning.md) fitted to the
        observed chunk-size distribution; the zero-recompile contract
        is unchanged (warmup compiles every rung, `_size_for` only ever
        picks warmed ones)."""
        import jax

        self.model = model
        self.params_fn = params_fn
        self.node_budget = int(node_budget)
        self.edge_budget = int(edge_budget)
        self.sizes = _ladder_sizes(ladder, int(max_batch_graphs))
        self.etypes = bool(etypes)
        self.mesh = mesh
        self._batch_sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            self._batch_sharding = NamedSharding(mesh, PartitionSpec())
        if feat_width is None:
            from deepdfa_tpu.graphs.batch import NUM_SUBKEY_FEATS

            feat_width = NUM_SUBKEY_FEATS
        self.feat_width = int(feat_width)

        def score(params, batch):
            # quantized entries (serve/quant.py): params arrive as the
            # int8/bf16 HBM tree and dequantize INSIDE the compiled
            # program (fused convert+scale, f32 accumulation)
            if params_transform is not None:
                params = params_transform(params)
            return jax.nn.sigmoid(model.apply(params, batch))

        self._score_jit = jax.jit(
            score, donate_argnums=_donate_batch_argnums()
        )
        self._compiled: dict[int, Any] = {}
        self._lowerings = 0

    #: efficiency-ledger site for this executor's compiles/executions
    ledger_tag = "serve_score"

    def _place(self, batch):
        import jax

        if self._batch_sharding is not None:
            return jax.device_put(batch, self._batch_sharding)
        return jax.device_put(batch)

    # -- grouping ------------------------------------------------------------

    def admit(self, spec) -> None:
        """Reject requests that can never fit a serving batch alone."""
        edges = spec.num_edges + spec.num_nodes  # + self loops
        if spec.num_nodes > self.node_budget or edges > self.edge_budget:
            raise RequestTooLarge(
                f"graph has {spec.num_nodes} nodes / {edges} edges "
                f"(incl. self loops); serving budgets are "
                f"{self.node_budget}/{self.edge_budget} "
                f"(raise serve.node_budget/serve.edge_budget)"
            )

    def bucket_key(self, spec) -> Hashable:
        return "graph"

    def capacity(self, key: Hashable) -> int:
        return self.sizes[-1]

    def fits(self, key: Hashable, chunk: Sequence, spec) -> bool:
        """Would adding `spec` keep the chunk inside the pack budgets?"""
        nodes = sum(s.num_nodes for s in chunk) + spec.num_nodes
        edges = (
            sum(s.num_edges + s.num_nodes for s in chunk)
            + spec.num_edges + spec.num_nodes
        )
        return nodes <= self.node_budget and edges <= self.edge_budget

    def _size_for(self, n: int) -> int:
        for s in self.sizes:
            if s >= n:
                return s
        return self.sizes[-1]

    # -- compilation ---------------------------------------------------------

    def _dummy_batch(self, size: int):
        from deepdfa_tpu.graphs.batch import pack

        return pack(
            [], size, self.node_budget, self.edge_budget,
            feat_width=self.feat_width, etypes=self.etypes,
        )

    def signatures(self) -> list[tuple]:
        return [
            (s, self.node_budget, self.edge_budget) for s in self.sizes
        ]

    def warmup(self) -> dict[str, float]:
        """AOT-compile every ladder size; {signature label: seconds}.
        Idempotent — re-warmup never recompiles."""
        import jax

        params = self.params_fn()
        report: dict[str, float] = {}
        for size in self.sizes:
            if size in self._compiled:
                continue
            t0 = time.perf_counter()
            batch = self._place(self._dummy_batch(size))
            self._compiled[size] = self._score_jit.lower(
                params, batch
            ).compile()
            dt = time.perf_counter() - t0
            self._lowerings += 1
            obs_metrics.REGISTRY.counter("serve/compiles").inc()
            obs_ledger.record_compile(
                self.ledger_tag, f"G{size}", self._compiled[size], dt
            )
            report[f"G{size}"] = round(dt, 3)
        obs_ledger.record_memory("warmup")
        return report

    def jit_lowerings(self) -> int:
        """AOT warmup compiles + any lazy jit call-cache entries — the
        zero-steady-state-recompiles guard (same contract as
        CombinedTrainer.jit_lowerings)."""
        return self._lowerings + self._score_jit._cache_size()

    # -- execution (pack -> dispatch -> fetch stages) -------------------------
    # The three stages are the pipeline contract every executor exports
    # (docs/serving.md "Pipelined execution"): `pack_chunk` is pure host
    # work, `dispatch` submits to the device WITHOUT syncing (JAX
    # dispatch is async), `fetch` is the one sync point. `execute` is
    # the serial composition for direct callers; the DynamicBatcher
    # drives the stages itself so the same code path serves both
    # pipeline_depth=0 and depth>0.

    def pack_chunk(self, key: Hashable, chunk: Sequence):
        """Host pack into the padded ladder batch; (signature label,
        packed). Host-only — its time belongs to the pack span, never
        to the ledger's measured execution window."""
        from deepdfa_tpu.graphs.batch import pack

        size = self._size_for(len(chunk))
        _observe_ladder_fill(f"G{size}", len(chunk), size)
        batch = pack(
            list(chunk), size, self.node_budget, self.edge_budget,
            feat_width=self.feat_width, etypes=self.etypes,
        )
        return f"G{size}", (size, batch)

    def dispatch(self, key: Hashable, packed):
        """H2D + submit the compiled ladder program; returns the
        un-synced device result (a future under async dispatch). The
        placed batch is donated to the executable on accelerator
        backends (`_donate_batch_argnums`)."""
        size, batch = packed
        batch = self._place(batch)
        fn = self._compiled.get(size, self._score_jit)
        return fn(self.params_fn(), batch)

    def fetch(self, handle, n: int) -> np.ndarray:
        """The sync point: block until the dispatched result is on
        host; [n] probabilities."""
        import jax

        return np.asarray(jax.device_get(handle))[:n]

    def execute(self, key: Hashable, chunk: Sequence) -> np.ndarray:
        """Pack + score one chunk; [len(chunk)] probabilities.

        Ledger window semantics (docs/efficiency.md): the rolling-MFU
        join measures dispatch->sync — host pack time is NOT counted as
        device time (it reports under the batcher's pack span)."""
        sig, packed = self.pack_chunk(key, chunk)
        t0 = time.perf_counter()
        out = self.fetch(self.dispatch(key, packed), len(chunk))
        obs_ledger.observe_execution(
            self.ledger_tag, sig, time.perf_counter() - t0
        )
        return out


class CombinedExecutor:
    """Per-signature AOT executables for the combined (text+graph)
    families — requests group by their PR-2 sequence bucket edge and
    each bucket's signature is `(T, rows, num_graphs)` with `rows` from
    the ONE `rows_for_bucket` formula (data/text.py), exactly the
    signatures combined training warms."""

    def __init__(
        self,
        model_cfg,
        params_fn: Callable[[], Any],
        tokenizer,
        seq_buckets: Sequence[int],
        token_budget: int,
        node_budget: int,
        edge_budget: int,
        is_t5: bool = False,
        params_transform: Callable[[Any], Any] | None = None,
        mesh=None,
    ):
        import jax

        from deepdfa_tpu.data.text import rows_for_bucket

        self.mesh = mesh
        self._batch_sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            self._batch_sharding = NamedSharding(mesh, PartitionSpec())
        self.model_cfg = model_cfg
        self.params_fn = params_fn
        self.tok = tokenizer
        self.buckets = tuple(int(b) for b in seq_buckets)
        if not self.buckets:
            raise ValueError(
                "CombinedExecutor needs data.seq_buckets (the serve "
                "bucket signatures); () has no edges to compile"
            )
        self.token_budget = int(token_budget)
        self.node_budget = int(node_budget)
        self.edge_budget = int(edge_budget)
        self.is_t5 = bool(is_t5)
        self.pad_id = int(getattr(model_cfg.encoder, "pad_token_id", 0))
        self._rows = {
            T: rows_for_bucket(T, self.token_budget, 1) for T in self.buckets
        }

        def score(params, batch):
            # quantized entries dequantize in-program (serve/quant.py)
            if params_transform is not None:
                params = params_transform(params)
            if self.is_t5:
                from deepdfa_tpu.models import t5 as t5m

                logits = t5m.defect_forward(
                    model_cfg, params, batch.input_ids,
                    graph_batch=batch.graphs, has_graph=batch.has_graph,
                    dropout_key=None,
                )
            else:
                from deepdfa_tpu.models import combined as cmb

                logits = cmb.forward(
                    model_cfg, params, batch.input_ids,
                    graph_batch=batch.graphs, has_graph=batch.has_graph,
                    dropout_key=None,
                )
            return jax.nn.softmax(logits)[:, 1]

        self._score_jit = jax.jit(
            score, donate_argnums=_donate_batch_argnums()
        )
        self._compiled: dict[int, Any] = {}
        self._lowerings = 0

    ledger_tag = "serve_combined"

    def _place(self, batch):
        import jax

        if self._batch_sharding is not None:
            return jax.device_put(batch, self._batch_sharding)
        return jax.device_put(batch)

    def ledger_signature(self, key: Hashable, n: int) -> str:
        T = int(key)
        return f"T{T}xR{self._rows[T]}"

    # payload: (token_ids [T0] np.int32, GraphSpec | None)

    def admit(self, payload) -> None:
        # the request must fit its bucket's batch ALONE, against the
        # same accounting collate() uses: every one of the bucket's
        # `rows` slots holds at least the 1-node/1-self-loop _EMPTY
        # placeholder (data/text.py) — a graph that only fits against a
        # smaller baseline would be silently degraded to
        # has_graph=False, scoring differently batched vs alone
        key = self.bucket_key(payload)  # raises on over-long text
        _, spec = payload
        if spec is not None:
            rows = self._rows[key]
            n_used = rows + spec.num_nodes - 1
            e_used = rows + spec.num_edges + spec.num_nodes - 1
            if n_used > self.node_budget or e_used > self.edge_budget:
                raise RequestTooLarge(
                    f"graph has {spec.num_nodes} nodes / "
                    f"{spec.num_edges + spec.num_nodes} edges (incl. self "
                    f"loops); with the T={key} bucket's {rows} placeholder "
                    f"rows that exceeds budgets "
                    f"{self.node_budget}/{self.edge_budget}"
                )

    def bucket_key(self, payload) -> Hashable:
        from deepdfa_tpu.data.text import token_lengths

        ids, _ = payload
        ln = int(token_lengths(np.asarray(ids)[None], self.pad_id)[0])
        for T in self.buckets:
            if ln <= T:
                return T
        raise RequestTooLarge(
            f"token length {ln} exceeds the largest bucket edge "
            f"{self.buckets[-1]}"
        )

    def capacity(self, key: Hashable) -> int:
        return self._rows[key]

    def fits(self, key: Hashable, chunk: Sequence, payload) -> bool:
        """Mirror collate()'s budget accounting EXACTLY (data/text.py):
        baseline = the bucket's full `rows` placeholder slots (1 node +
        1 self loop each), each real graph costs its delta over one
        placeholder. If this admits a chunk, collate degrades nothing —
        which is what keeps batched scores bit-identical to singleton
        scores (a degraded has_graph=False row would score text-only
        batched but with its graph alone)."""
        rows = self._rows[key]
        n_used = rows
        e_used = rows
        for _, spec in list(chunk) + [payload]:
            if spec is not None:
                n_used += spec.num_nodes - 1
                e_used += spec.num_edges + spec.num_nodes - 1
        return n_used <= self.node_budget and e_used <= self.edge_budget

    def signatures(self) -> list[tuple]:
        return [(T, self._rows[T], self._rows[T]) for T in self.buckets]

    def _collate(self, T: int, chunk: Sequence):
        from deepdfa_tpu.data.text import _fit_width, collate

        rows = self._rows[T]
        if chunk:
            tok = np.stack(
                [_fit_width(ids, T, self.pad_id) for ids, _ in chunk]
            )
        else:
            tok = np.zeros((0, T), np.int32)
        graphs_by_id = {
            i: spec
            for i, (_, spec) in enumerate(chunk)
            if spec is not None
        }
        return collate(
            tok, [0] * len(chunk), list(range(len(chunk))), graphs_by_id,
            batch_rows=rows, node_budget=self.node_budget,
            edge_budget=self.edge_budget, pad_id=self.pad_id,
        )

    def warmup(self) -> dict[str, float]:
        import jax

        params = self.params_fn()
        report: dict[str, float] = {}
        for T in self.buckets:
            if T in self._compiled:
                continue
            t0 = time.perf_counter()
            batch = self._place(self._collate(T, []))
            self._compiled[T] = self._score_jit.lower(
                params, batch
            ).compile()
            dt = time.perf_counter() - t0
            self._lowerings += 1
            obs_metrics.REGISTRY.counter("serve/compiles").inc()
            obs_ledger.record_compile(
                self.ledger_tag, f"T{T}xR{self._rows[T]}",
                self._compiled[T], dt,
            )
            report[f"T{T}xR{self._rows[T]}"] = round(dt, 3)
        obs_ledger.record_memory("warmup")
        return report

    def jit_lowerings(self) -> int:
        return self._lowerings + self._score_jit._cache_size()

    # -- execution (the same pack/dispatch/fetch stage contract as
    # GgnnExecutor; docs/serving.md "Pipelined execution") -------------------

    def pack_chunk(self, key: Hashable, chunk: Sequence):
        sig = self.ledger_signature(key, len(chunk))
        _observe_ladder_fill(sig, len(chunk), self._rows[int(key)])
        return sig, (int(key), self._collate(int(key), chunk))

    def dispatch(self, key: Hashable, packed):
        T, batch = packed
        batch = self._place(batch)
        fn = self._compiled.get(T, self._score_jit)
        return fn(self.params_fn(), batch)

    def fetch(self, handle, n: int) -> np.ndarray:
        import jax

        return np.asarray(jax.device_get(handle))[:n]

    def execute(self, key: Hashable, chunk: Sequence) -> np.ndarray:
        # ledger window = dispatch->sync, host collate excluded (the
        # same window-semantics contract as GgnnExecutor.execute)
        sig, packed = self.pack_chunk(key, chunk)
        t0 = time.perf_counter()
        out = self.fetch(self.dispatch(key, packed), len(chunk))
        obs_ledger.observe_execution(
            self.ledger_tag, sig, time.perf_counter() - t0
        )
        return out


class DynamicBatcher:
    """Bounded-queue scheduler over an executor's bucket signatures.

    Two drive modes sharing the SAME grouping/flush/execute code path:
      - `start()` spawns the scheduler thread (online serving) — batches
        flush when a signature group is full or its oldest request aged
        past `max_batch_delay_s`;
      - `score_all(payloads)` drives synchronously (offline `score` CLI,
        deterministic: full groups flush as they fill, the tail force-
        flushes).

    Pipelined execution (docs/serving.md "Pipelined execution"):
    `pipeline_depth > 0` splits every batch into the executor's
    pack -> dispatch -> fetch stages. The drive side (scheduler thread
    or offline drain) packs and submits WITHOUT syncing, keeping at
    most `pipeline_depth` dispatched-but-unsynced batches in flight
    (backpressure blocks the dispatcher, never deepens the window); the
    FIFO fetch stage syncs results, resolves request futures, and owns
    the per-request `device_s` attribution plus the ledger's
    rolling-MFU join (FIFO-union windows, `DeviceWindow`). Online, the
    fetch stage runs on its own thread next to the scheduler; offline
    drives (`score_all`/`drain`) sync the oldest batch inline when the
    window fills — same stages, no cross-thread handoff per batch.
    Arrival order, grouping, and deterministic packing are unchanged —
    scores stay bit-identical to the depth=0 serial path, which itself
    is byte-identical to the historical inline execute.
    """

    def __init__(
        self,
        executor,
        queue_limit: int = 256,
        max_batch_delay_s: float = 0.025,
        on_batch: Callable[[], None] | None = None,
        slo=None,
        pipeline_depth: int = 0,
    ):
        self.executor = executor
        self.queue_limit = int(queue_limit)
        self.max_batch_delay_s = float(max_batch_delay_s)
        self.pipeline_depth = max(0, int(pipeline_depth))
        self.on_batch = on_batch
        #: optional obs/slo.py:SloEngine — queue depth + batch occupancy
        #: feed the rolling windows (request latency is observed by the
        #: server/driver once the final HTTP status is known)
        self.slo = slo
        self._lock = threading.Condition()
        self._pending: "OrderedDict[Hashable, deque[ScoreRequest]]" = (
            OrderedDict()
        )
        self._n_pending = 0
        self._closed = False
        self._thread: threading.Thread | None = None
        #: bounded recent-latency window for host-side quantiles
        #: (/stats, bench_serve) — the registry histogram keeps only
        #: count/mean/max
        self.recent_latencies: deque[float] = deque(maxlen=4096)
        self.batches_run = 0
        r = obs_metrics.REGISTRY
        self._m_requests = r.counter("serve/requests")
        self._m_rejected = r.counter("serve/rejected")
        self._m_batches = r.counter("serve/batches")
        self._m_depth = r.gauge("serve/queue_depth")
        self._m_occupancy = r.histogram("serve/batch_occupancy")
        self._m_latency = r.histogram("serve/latency_seconds")
        self._m_queue_wait = r.histogram("serve/queue_wait_seconds")
        self._m_device = r.histogram("serve/device_seconds")
        # -- pipelined execution state (pipeline_depth > 0) ------------------
        #: FIFO of dispatched-but-unsynced batches, synced in submission
        #: order by the fetch thread; _n_inflight counts batches whose
        #: fetch has not COMPLETED yet (popped-but-syncing still holds
        #: its slot), both guarded by _fetch_cv
        self._inflight: deque = deque()
        self._n_inflight = 0
        self._fetch_cv = threading.Condition()
        self._fetch_thread: threading.Thread | None = None
        self._fetch_stop = False
        #: FIFO-union device-busy attribution shared by both depths —
        #: at depth 0 it degenerates to plain submit->sync windows
        self._window = DeviceWindow()
        self._m_pipe_depth = r.histogram("serve/pipeline/depth")
        self._m_pack = r.histogram("serve/pipeline/pack_seconds")
        self._m_dispatch = r.histogram("serve/pipeline/dispatch_seconds")
        self._m_fetch = r.histogram("serve/pipeline/fetch_seconds")
        self._m_pipe_batches = r.counter("serve/pipeline/batches")
        self._m_busy = r.counter("serve/pipeline/device_busy_seconds")
        self._m_idle = r.counter("serve/pipeline/device_idle_seconds")
        self._m_overlap = r.counter("serve/pipeline/overlap_seconds")
        self._m_idle_frac = r.gauge("serve/pipeline/device_idle_fraction")

    # -- admission -----------------------------------------------------------

    def submit(
        self,
        payload,
        request_id: str | None = None,
        frontend_s: float | None = None,
    ) -> ScoreRequest:
        """Enqueue one request; raises QueueFull (admission control) or
        RequestTooLarge (can never fit a batch). `request_id` is the
        ingress-assigned id (a fresh one is minted for direct callers);
        `frontend_s` carries the extraction time measured upstream so
        the request's stage attribution stays on the request."""
        self.executor.admit(payload)
        key = self.executor.bucket_key(payload)
        req = ScoreRequest(payload)
        if request_id is not None:
            req.request_id = request_id
        req.frontend_s = frontend_s
        with self._lock:
            if self._closed:
                raise RuntimeError("batcher is closed")
            if self._n_pending >= self.queue_limit:
                self._m_rejected.inc()
                raise QueueFull(
                    f"serve queue at limit ({self.queue_limit}); retry "
                    f"later"
                )
            self._pending.setdefault(key, deque()).append(req)
            self._n_pending += 1
            self._m_requests.inc()
            self._m_depth.set(self._n_pending)
            if self.slo is not None:
                self.slo.set_queue_depth(self._n_pending)
            self._lock.notify_all()
        return req

    def stats(self) -> dict:
        with self._lock:
            depth = self._n_pending
        lat = sorted(self.recent_latencies)
        with self._fetch_cv:
            in_flight = self._n_inflight
        return {
            "queue_depth": depth,
            "batches": self.batches_run,
            "latency_p50_s": percentile(lat, 0.50),
            "latency_p99_s": percentile(lat, 0.99),
            "jit_lowerings": self.executor.jit_lowerings(),
            "pipeline_depth": self.pipeline_depth,
            "pipeline_in_flight": in_flight,
        }

    # -- scheduling ----------------------------------------------------------

    def _pop_chunk(self, key: Hashable) -> list[ScoreRequest]:
        """Pop the largest budget-respecting prefix of a group (holding
        the lock). Arrival order within the group is preserved — that
        plus deterministic packing is what makes the offline drive
        replayable."""
        q = self._pending[key]
        cap = self.executor.capacity(key)
        chunk: list[ScoreRequest] = []
        payloads: list = []
        while q and len(chunk) < cap:
            nxt = q[0]
            if payloads and not self.executor.fits(
                key, payloads, nxt.payload
            ):
                break
            chunk.append(q.popleft())
            payloads.append(chunk[-1].payload)
        if not q:
            del self._pending[key]
        self._n_pending -= len(chunk)
        self._m_depth.set(self._n_pending)
        if self.slo is not None:
            self.slo.set_queue_depth(self._n_pending)
        return chunk

    def _take_ready(self, force: bool = False):
        """(key, chunk) of the next batch to run, or (None, wait_s).

        Full groups flush immediately; otherwise the OLDEST pending
        request's age decides — past the delay the scheduler flushes its
        group partially (force skips the wait: offline drain)."""
        now = time.monotonic()
        oldest_key = None
        oldest_t = None
        for key, q in self._pending.items():
            cap = self.executor.capacity(key)
            if len(q) >= cap:
                return key, None
            t = q[0].t_submit
            if oldest_t is None or t < oldest_t:
                oldest_key, oldest_t = key, t
        if oldest_key is None:
            return None, None
        if force or now - oldest_t >= self.max_batch_delay_s:
            return oldest_key, None
        return None, self.max_batch_delay_s - (now - oldest_t)

    def _begin_batch(
        self, key: Hashable, chunk: list[ScoreRequest]
    ) -> bool:
        """Drive-side prologue shared by the serial and pipelined paths:
        hot-swap poll, queue-wait attribution, and the backdated
        queue-wait trace windows. Returns whether tracing is on."""
        if self.on_batch is not None:
            try:
                self.on_batch()  # e.g. registry.maybe_reload (hot swap)
            except Exception:
                pass  # a failed poll must never fail the batch
        t0 = time.monotonic()
        tracing = obs_trace.enabled()
        for req in chunk:
            req.queue_wait_s = t0 - req.t_submit
            req.batch_size = len(chunk)
            self._m_queue_wait.observe(req.queue_wait_s)
        if tracing:
            # the queue-wait windows, placed at their TRUE submit times
            # (monotonic seconds and trace us share CLOCK_MONOTONIC) on
            # a dedicated synthetic track: on this thread's own track
            # the per-thread increasing-ts nudge would clamp backdated
            # windows forward (the StepTimer hazard). Windows first —
            # they arrive FIFO-sorted — then the flow steps (each at a
            # ts >= the last window start and <= t0, so every flow
            # still lands inside its request's window even if nudged)
            for req in chunk:
                obs_trace.complete_event(
                    "queue_wait", ts_us=req.t_submit * 1e6,
                    dur_us=req.queue_wait_s * 1e6, cat="serve",
                    tid=obs_trace.QUEUE_TRACK_TID,
                    track_name="serve-queue",
                    args={"request_id": req.request_id},
                )
            for req in chunk:
                obs_trace.flow(
                    "request", req.request_id, "t", cat="serve",
                    ts_us=(req.t_submit + req.queue_wait_s / 2) * 1e6,
                    tid=obs_trace.QUEUE_TRACK_TID,
                    track_name="serve-queue",
                )
        return tracing

    def _complete_batch(
        self,
        key: Hashable,
        sig: str,
        chunk: list[ScoreRequest],
        probs,
        t_submit: float,
        t_sync: float,
    ) -> None:
        """Fetch-side epilogue (drive thread at depth 0, fetch thread
        otherwise): device-window attribution, the ledger's rolling-MFU
        join, SLO/metrics bookkeeping, and future resolution.

        Window semantics (docs/serving.md): the observed "device" window
        is this batch's FIFO-union busy share of its dispatch->sync
        interval — host pack time is excluded (it has its own span and
        histogram), and under pipelining the part of the interval spent
        waiting behind the previous batch is not double-counted. Rolling
        MFU, `serve/device_seconds`, and per-request `device_s` all use
        this busy share."""
        idle0 = self._window.idle_s
        busy = self._window.observe(t_submit, t_sync)
        self._m_busy.inc(busy)
        self._m_idle.inc(self._window.idle_s - idle0)
        frac = self._window.idle_fraction()
        if frac is not None:
            self._m_idle_frac.set(frac)
        tag = getattr(self.executor, "ledger_tag", None)
        if tag is not None:
            obs_ledger.observe_execution(tag, sig, busy)
        self.batches_run += 1
        self._m_batches.inc()
        self._m_pipe_batches.inc()
        self._m_device.observe(busy)
        occupancy = len(chunk) / max(1, self.executor.capacity(key))
        self._m_occupancy.observe(occupancy)
        if self.slo is not None:
            self.slo.observe_batch(occupancy)
        for req, p in zip(chunk, probs):
            req.device_s = busy
            req.set_result(float(p))
            self._m_latency.observe(req.latency_s)
            self.recent_latencies.append(req.latency_s)

    def _run_batch(self, key: Hashable, chunk: list[ScoreRequest]) -> None:
        """Serial path (pipeline_depth == 0): pack -> dispatch -> fetch
        inline on the drive thread. The stage split is the same one the
        pipelined path uses; only the threading differs."""
        tracing = self._begin_batch(key, chunk)
        try:
            with obs_trace.span(
                "pack", cat="serve", signature=str(key),
                batch_size=len(chunk),
            ):
                tp = time.perf_counter()
                sig, packed = self.executor.pack_chunk(
                    key, [r.payload for r in chunk]
                )
                self._m_pack.observe(time.perf_counter() - tp)
            with obs_trace.span(
                "device_execute", cat="serve", signature=str(key),
                batch_size=len(chunk),
                request_ids=[r.request_id for r in chunk] if tracing
                else None,
            ):
                if tracing:
                    for req in chunk:
                        obs_trace.flow(
                            "request", req.request_id, "f", cat="serve"
                        )
                t_submit = time.perf_counter()
                handle = self.executor.dispatch(key, packed)
                td = time.perf_counter()
                self._m_dispatch.observe(td - t_submit)
                probs = self.executor.fetch(handle, len(chunk))
                t_sync = time.perf_counter()
                self._m_fetch.observe(t_sync - td)
        except Exception as e:
            # a batch that died with RESOURCE_EXHAUSTED is exactly the
            # moment the HBM ledger exists for: dump a postmortem (no-op
            # unless the flight recorder is installed) before the error
            # fans out to the requests
            obs_flight.note_exception(e, where="serve_batch")
            for req in chunk:
                req.set_error(e)
            return
        self._complete_batch(key, sig, chunk, probs, t_submit, t_sync)

    # -- pipelined path (pipeline_depth > 0) ---------------------------------

    def _dispatch_batch(
        self, key: Hashable, chunk: list[ScoreRequest]
    ) -> None:
        """Pipelined drive side: pack + submit WITHOUT syncing. Blocks
        while `pipeline_depth` batches are already in flight — the
        bounded window IS the backpressure, so unsynced device work and
        staged host batches both stay bounded."""
        tracing = self._begin_batch(key, chunk)
        try:
            with obs_trace.span(
                "pack", cat="serve", signature=str(key),
                batch_size=len(chunk),
            ):
                tp = time.perf_counter()
                sig, packed = self.executor.pack_chunk(
                    key, [r.payload for r in chunk]
                )
                pack_s = time.perf_counter() - tp
                self._m_pack.observe(pack_s)
        except Exception as e:
            obs_flight.note_exception(e, where="serve_batch")
            for req in chunk:
                req.set_error(e)
            return
        # acquire the in-flight slot BEFORE submitting: dispatched-but-
        # unsynced batches never exceed pipeline_depth. Online, the
        # FIFO fetch thread frees slots; offline (no scheduler thread)
        # the drive syncs the oldest batch inline instead — single-
        # threaded software pipelining, because a cross-thread handoff
        # per batch costs more GIL ping-pong than the tiny offline
        # epilogue it would offload
        if self._fetch_thread is not None:
            with self._fetch_cv:
                while self._n_inflight >= self.pipeline_depth:
                    self._fetch_cv.wait(0.25)
                self._n_inflight += 1
                overlapped = self._n_inflight > 1
                self._m_pipe_depth.observe(self._n_inflight)
        else:
            while True:
                with self._fetch_cv:
                    if self._n_inflight < self.pipeline_depth:
                        self._n_inflight += 1
                        overlapped = self._n_inflight > 1
                        self._m_pipe_depth.observe(self._n_inflight)
                        break
                self._sync_oldest()
        try:
            with obs_trace.span(
                "dispatch", cat="serve", signature=str(key),
                batch_size=len(chunk),
                request_ids=[r.request_id for r in chunk] if tracing
                else None,
            ):
                if tracing:
                    for req in chunk:
                        obs_trace.flow(
                            "request", req.request_id, "t", cat="serve"
                        )
                t_submit = time.perf_counter()
                handle = self.executor.dispatch(key, packed)
                dispatch_s = time.perf_counter() - t_submit
                self._m_dispatch.observe(dispatch_s)
        except Exception as e:
            obs_flight.note_exception(e, where="serve_batch")
            for req in chunk:
                req.set_error(e)
            with self._fetch_cv:
                self._n_inflight -= 1
                self._fetch_cv.notify_all()
            return
        if overlapped:
            # host stage seconds spent while the device already held an
            # in-flight batch: the overlap the pipeline buys
            self._m_overlap.inc(pack_s + dispatch_s)
        with self._fetch_cv:
            self._inflight.append((key, sig, chunk, handle, t_submit))
            self._fetch_cv.notify_all()

    def _sync_oldest(self) -> bool:
        """Fetch + resolve the oldest in-flight batch on the CALLING
        thread (the offline drive's fetch stage); False if none."""
        with self._fetch_cv:
            if not self._inflight:
                return False
            item = self._inflight.popleft()
        try:
            self._fetch_one(*item)
        finally:
            with self._fetch_cv:
                self._n_inflight -= 1
                self._fetch_cv.notify_all()
        return True

    def _fetch_loop(self) -> None:
        """FIFO fetch stage: sync each dispatched batch in submission
        order, resolve its futures, run the epilogue. Exits once stop
        was requested AND the in-flight FIFO has drained."""
        while True:
            with self._fetch_cv:
                while not self._inflight and not self._fetch_stop:
                    self._fetch_cv.wait(0.25)
                if not self._inflight:
                    return
                item = self._inflight.popleft()
            try:
                self._fetch_one(*item)
            finally:
                with self._fetch_cv:
                    self._n_inflight -= 1
                    self._fetch_cv.notify_all()

    def _fetch_one(
        self,
        key: Hashable,
        sig: str,
        chunk: list[ScoreRequest],
        handle,
        t_submit: float,
    ) -> None:
        tracing = obs_trace.enabled()
        try:
            with obs_trace.span(
                "fetch", cat="serve", signature=str(key),
                batch_size=len(chunk),
                request_ids=[r.request_id for r in chunk] if tracing
                else None,
            ):
                if tracing:
                    for req in chunk:
                        obs_trace.flow(
                            "request", req.request_id, "f", cat="serve"
                        )
                tf = time.perf_counter()
                probs = self.executor.fetch(handle, len(chunk))
                t_sync = time.perf_counter()
                self._m_fetch.observe(t_sync - tf)
        except Exception as e:
            obs_flight.note_exception(e, where="serve_fetch")
            for req in chunk:
                req.set_error(e)
            return
        self._complete_batch(key, sig, chunk, probs, t_submit, t_sync)

    def _ensure_fetch_thread(self) -> None:
        if self._fetch_thread is None:
            self._fetch_stop = False
            self._fetch_thread = threading.Thread(
                target=self._fetch_loop, name="serve-fetch", daemon=True
            )
            self._fetch_thread.start()

    def _wait_inflight(self, timeout_s: float = 60.0) -> None:
        """Block until every dispatched batch has been fetched and its
        futures resolved (the pipelined half of drain); no-op at
        depth 0."""
        deadline = time.monotonic() + timeout_s
        with self._fetch_cv:
            while self._n_inflight > 0:
                if time.monotonic() >= deadline:
                    raise RuntimeError(
                        f"{self._n_inflight} pipelined batches still in "
                        f"flight after {timeout_s:.0f}s"
                    )
                self._fetch_cv.wait(0.25)

    def _stop_fetch(self) -> None:
        t = self._fetch_thread
        if t is None:
            return
        with self._fetch_cv:
            self._fetch_stop = True
            self._fetch_cv.notify_all()
        t.join(timeout=10)
        self._fetch_thread = None

    def pipeline_stats(self) -> dict:
        """Device-window attribution snapshot — bench_serve/bench_load
        stamp `serve_device_idle_fraction` from this. Valid at any
        depth (the serial path feeds the same window)."""
        return {
            "depth": self.pipeline_depth,
            "device_busy_s": self._window.busy_s,
            "device_idle_s": self._window.idle_s,
            "device_idle_fraction": self._window.idle_fraction(),
        }

    def _drain_once(self, force: bool = False) -> bool:
        """Run at most one batch; True if one ran."""
        with self._lock:
            key, wait = self._take_ready(force=force)
            if key is None:
                return False
            chunk = self._pop_chunk(key)
        if chunk:
            if self.pipeline_depth > 0:
                self._dispatch_batch(key, chunk)
            else:
                self._run_batch(key, chunk)
        return bool(chunk)

    def drain(self) -> None:
        """Offline: run batches until the queue is empty (full groups
        first, then force-flush the tails). Pipelined, additionally
        waits for the in-flight window to empty so every future is
        resolved on return."""
        while True:
            if not self._drain_once(force=True):
                with self._lock:
                    if self._n_pending == 0:
                        break
        if self._fetch_thread is None:
            while self._sync_oldest():
                pass
        self._wait_inflight()

    def score_all(
        self,
        payloads: Sequence,
        request_ids: Sequence[str] | None = None,
        frontend_seconds: Sequence[float] | None = None,
    ) -> list[ScoreRequest]:
        """Synchronously score a payload sequence through the SAME
        grouping/flush path the online scheduler uses. Submissions that
        hit the queue limit drain in place instead of rejecting — the
        offline caller wants completion, not backpressure. Optional
        per-payload `request_ids`/`frontend_seconds` carry the ingress
        identity and frontend timing the offline driver measured."""
        if self._thread is not None:
            raise RuntimeError(
                "score_all is the offline drive; the scheduler thread "
                "is running"
            )
        reqs: list[ScoreRequest] = []
        for i, p in enumerate(payloads):
            rid = request_ids[i] if request_ids is not None else None
            fs = (
                frontend_seconds[i]
                if frontend_seconds is not None else None
            )
            while True:
                try:
                    reqs.append(
                        self.submit(p, request_id=rid, frontend_s=fs)
                    )
                    break
                except QueueFull:
                    self._drain_once(force=True)
                except RequestTooLarge as e:
                    # per-row fault isolation: one over-budget graph
                    # becomes a failed row, never a crashed job
                    req = ScoreRequest(p)
                    if rid is not None:
                        req.request_id = rid
                    req.frontend_s = fs
                    req.set_error(e)
                    reqs.append(req)
                    break
            # full groups execute as they fill (bounded memory)
            while self._drain_once(force=False):
                pass
        self.drain()
        return reqs

    # -- online mode ---------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        if self.pipeline_depth > 0:
            # online mode pairs the scheduler with the dedicated FIFO
            # fetch thread (offline drives sync inline instead)
            self._ensure_fetch_thread()
        self._thread = threading.Thread(
            target=self._loop, name="serve-batcher", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while True:
            with self._lock:
                if self._closed and self._n_pending == 0:
                    return
                # on close, force-flush what is queued instead of letting
                # submitted requests hang
                key, wait = self._take_ready(force=self._closed)
                chunk = self._pop_chunk(key) if key is not None else None
                if chunk is None:
                    self._lock.wait(
                        timeout=wait if wait is not None else 0.25
                    )
                    continue
            if self.pipeline_depth > 0:
                self._dispatch_batch(key, chunk)
            else:
                self._run_batch(key, chunk)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._lock.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if self._fetch_thread is not None:
            # the scheduler force-flushed on close; let the fetch stage
            # resolve what it dispatched, then retire the thread
            self._wait_inflight()
            self._stop_fetch()
