"""Request preprocessing for online inference (docs/serving.md).

Raw C/C++ source -> model-ready `GraphSpec`, through exactly the
training extraction path (`data/pipeline.py:graph_from_cpg` +
`to_graph_spec` against the run's vocabularies), so a served function is
featurized bit-identically to how the training corpus was.

Two parser routes share that path:
  - the built-in frontend parser (default — hermetic, no JVM);
  - a POOLED Joern session (`serve.use_joern`): a bounded pool of
    `frontend/joern_session.py` JVMs, each with its own PR-3 bounded
    auto-restart, checked out per request and replaced when dead.

A content-keyed feature cache (sha256 of source + the feature-spec /
gtype identity) sits in front of both routes: repeat functions — the
common case for heavy traffic scoring the same hot code — skip the
frontend entirely. Failures are cached too (a function the parser
cannot handle stays unparseable until its bytes change).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import tempfile
import threading
import time
from collections import OrderedDict
from pathlib import Path
from typing import Any, Callable

import numpy as np

from deepdfa_tpu.obs import metrics as obs_metrics

logger = logging.getLogger(__name__)


class FrontendError(ValueError):
    """The function could not be turned into a model graph."""


@dataclasses.dataclass(frozen=True)
class Features:
    """One cached extraction: the batchable GraphSpec plus the per-node
    source lines (1-based, in the FUNCTION's own coordinates) the
    line-attribution paths map node scores back through
    (serve/localize.py, deepdfa_tpu/scan/)."""

    spec: Any  # GraphSpec
    node_lines: np.ndarray  # [n] int32


class FeatureCache:
    """Bounded content-keyed LRU for extraction results (hits count in
    the serve metrics; 0 entries disables)."""

    def __init__(self, max_entries: int = 1024):
        self.max_entries = int(max_entries)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, Any]" = OrderedDict()
        r = obs_metrics.REGISTRY
        self._hits = r.counter("serve/cache_hits")
        self._misses = r.counter("serve/cache_misses")

    def get(self, key: str):
        """(hit, value) — value may legitimately be None (cached failure)."""
        if not self.max_entries:
            self._misses.inc()
            return False, None
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                self._hits.inc()
                return True, self._entries[key]
        self._misses.inc()
        return False, None

    def put(self, key: str, value) -> None:
        if not self.max_entries:
            return
        with self._lock:
            self._entries[key] = value
            self._entries.move_to_end(key)
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


#: the process-wide feature store `shared_cache` hands out — scan and
#: serve both preprocess through it, so a repo scan warm-fills the cache
#: online requests hit (and vice versa) instead of each keeping its own
#: content-keyed store. Safe to share across configs: every key pins the
#: feat-spec/gtype/parser identity (`RequestPreprocessor.content_key`).
_SHARED_CACHE: FeatureCache | None = None
_SHARED_LOCK = threading.Lock()


def shared_cache(max_entries: int = 1024) -> FeatureCache:
    """The one process-wide FeatureCache. Created on first use; later
    callers asking for more capacity grow it (never shrink — a smaller
    config must not evict another subsystem's warm entries)."""
    global _SHARED_CACHE
    with _SHARED_LOCK:
        if _SHARED_CACHE is None:
            _SHARED_CACHE = FeatureCache(max_entries)
        elif int(max_entries) > _SHARED_CACHE.max_entries:
            _SHARED_CACHE.max_entries = int(max_entries)
        return _SHARED_CACHE


class SessionPool:
    """Bounded pool of frontend sessions (Joern JVMs in production;
    anything with close() in tests).

    Sessions are created lazily up to `size`, checked out exclusively,
    and REPLACED (closed + recreated on next checkout) when the borrower
    saw an exception — the session-internal auto-restart
    (JoernSession.max_restarts) handles transient hangs; the pool
    handles sessions that died for good."""

    def __init__(self, factory: Callable[[int], Any], size: int = 1):
        self.factory = factory
        self.size = max(1, int(size))
        # one condition guards both the free list and the creation
        # budget: a discard frees CREATION capacity (not a queued
        # session), so waiters must re-check both paths when notified —
        # a bare Queue.get() would sleep through that forever
        self._cond = threading.Condition()
        self._free: list[Any] = []
        self._created = 0
        self._next_id = 0
        self.replaced = 0
        self._closed = False

    def _checkout(self):
        with self._cond:
            while True:
                if self._closed:
                    raise RuntimeError("session pool is closed")
                if self._free:
                    return self._free.pop()
                if self._created < self.size:
                    self._created += 1
                    self._next_id += 1
                    worker_id = self._next_id - 1
                    break
                self._cond.wait()
        # construct OUTSIDE the lock (a Joern JVM spawn takes seconds)
        try:
            return self.factory(worker_id)
        except Exception:
            with self._cond:
                self._created -= 1
                self._cond.notify()
            raise

    def session(self):
        """Context manager: checkout, yield, return — or discard on error."""
        pool = self

        class _Lease:
            def __enter__(self):
                self.s = pool._checkout()
                return self.s

            def __exit__(self, exc_type, exc, tb):
                if exc_type is None:
                    pool._return(self.s)
                else:
                    # the borrower's exception already propagates; the
                    # dead session just quietly leaves the pool
                    pool._discard(self.s)
                return False

        return _Lease()

    def _return(self, s) -> None:
        with self._cond:
            self._free.append(s)
            self._cond.notify()

    def _discard(self, s) -> None:
        try:
            s.close()
        except Exception:
            pass
        with self._cond:
            self._created -= 1
            self.replaced += 1
            self._cond.notify()  # creation capacity freed: wake a waiter

    def close(self) -> None:
        with self._cond:
            self._closed = True
            free, self._free = self._free, []
            self._cond.notify_all()
        for s in free:
            try:
                s.close()
            except Exception:
                pass


class RequestPreprocessor:
    """source text -> GraphSpec, cached, timed, parser-routed."""

    def __init__(
        self,
        cfg,
        vocabs,
        use_joern: bool = False,
        joern_pool: SessionPool | None = None,
        cache_entries: int = 1024,
        cache: FeatureCache | None = None,
    ):
        self.cfg = cfg
        self.vocabs = vocabs
        self.gtype = cfg.data.gtype
        self.struct_feats = bool(cfg.data.feat.struct_feats)
        self.max_defs = cfg.data.feat.max_defs
        # an explicit `cache` joins an existing store (ScoringService and
        # the repo scanner both pass `shared_cache(...)` — satellite 6's
        # one-namespace rule); None keeps a private store (tests, tools)
        self.cache = cache if cache is not None else FeatureCache(
            cache_entries
        )
        self.use_joern = bool(use_joern)
        self.pool = joern_pool
        if self.use_joern and self.pool is None:
            from deepdfa_tpu.frontend import joern_session

            if not joern_session.available():
                raise FrontendError(
                    "serve.use_joern=true but no `joern` binary on PATH"
                )
            scfg = cfg.serve
            self.pool = SessionPool(
                lambda i: joern_session.JoernSession(
                    worker_id=i, timeout=scfg.joern_timeout_s
                ),
                size=scfg.joern_pool_size,
            )
        r = obs_metrics.REGISTRY
        self._seconds = r.histogram("serve/frontend_seconds")
        self._failed = r.counter("serve/failed")
        # the cache key pins every knob that changes the extracted
        # bytes INCLUDING the vocabulary content: with the process-wide
        # shared store, two runs whose feat specs share a name but whose
        # train splits built different vocabs must never trade entries
        self._key_suffix = (
            f"|{cfg.data.feat.name}|{self.gtype}|joern={self.use_joern}"
            f"|vocab={self._vocab_digest()}"
        )

    def _vocab_digest(self) -> str:
        payload = json.dumps(
            {k: v.to_json() for k, v in sorted(self.vocabs.items())},
            sort_keys=True,
        )
        return hashlib.sha256(payload.encode()).hexdigest()[:16]

    def content_key(self, code: str) -> str:
        h = hashlib.sha256(code.encode("utf-8", "replace")).hexdigest()
        return h + self._key_suffix

    def features(self, code: str, request_id: int = -1):
        """GraphSpec for one function; raises FrontendError on functions
        the frontend cannot handle (cached either way)."""
        return self.features_full(code, request_id).spec

    def features_full(self, code: str, request_id: int = -1) -> Features:
        """GraphSpec + per-node source lines — what the line-attribution
        paths need; `features` is the spec-only view of the same cache
        entry."""
        key = self.content_key(code)
        hit, cached = self.cache.get(key)
        if hit:
            if cached is None:
                self._failed.inc()
                raise FrontendError("unparseable function (cached)")
            return cached
        t0 = time.perf_counter()
        try:
            feats = self._extract(code, request_id)
        finally:
            self._seconds.observe(time.perf_counter() - t0)
        self.cache.put(key, feats)
        if feats is None:
            self._failed.inc()
            raise FrontendError(
                "function could not be parsed into a CFG graph"
            )
        return feats

    def _extract(self, code: str, request_id: int) -> Features | None:
        from deepdfa_tpu.data.pipeline import (
            extract_graph,
            graph_from_cpg,
            to_graph_spec,
        )

        if self.use_joern:
            cpg = self._joern_cpg(code)
            eg = (
                None if cpg is None else graph_from_cpg(
                    cpg, request_id, max_defs=self.max_defs,
                    gtype=self.gtype, struct_feats=self.struct_feats,
                )
            )
        else:
            eg = extract_graph(
                code, request_id, max_defs=self.max_defs,
                gtype=self.gtype, struct_feats=self.struct_feats,
            )
        if eg is None:
            return None
        return Features(
            to_graph_spec(eg, self.vocabs), eg.node_lines.copy()
        )

    def _joern_cpg(self, code: str):
        """One pooled-JVM round trip: tmp file -> export -> Cpg."""
        from deepdfa_tpu.frontend.joern_io import load_joern_cpg

        with self.pool.session() as sess:
            with tempfile.TemporaryDirectory(prefix="serve-joern-") as d:
                src = Path(d) / "request.c"
                src.write_text(code)
                sess.import_code(src)
                sess.export_cpg_json(src)  # writes <src>.{nodes,edges}.json
                try:
                    return load_joern_cpg(src)
                except (OSError, ValueError) as e:
                    logger.warning("joern export unreadable: %s", e)
                    return None

    def close(self) -> None:
        if self.pool is not None:
            self.pool.close()
