"""Two-stage cascaded inference + combined/t5 family serving support
(docs/cascade.md).

The paper's economics, pushed to the serve path: the ~25k-param GGNN is
cheap enough to score EVERY request, and the expensive combined/t5
transformer is only worth running on requests the GGNN is *uncertain*
about. With `serve.cascade=true`, `/score` becomes:

    stage 1 (always)   GGNN executor -> prob p1
    calibrate          p_cal = temperature_scale(p1, T)
    in band?           lo <= p_cal < hi  (eval/calibrate.py fits both)
    stage 2 (band only) combined/t5 executor -> the served prob

One endpoint, per-stage SLO attribution (`cascade_stage1` /
`cascade_stage2` in the rolling windows), an escalation-rate gauge, and
a shed-before-screen degradation mode: when the stage-2 queue backs up
past `serve.cascade_shed_depth_fraction`, new escalations answer with
their stage-1 score instead of queueing device time the fleet doesn't
have — the cascade degrades to the cheap screen first, mirroring the
fleet admission layer's cascade-aware shed (fleet/admission.py).

This module also owns the pieces that make the combined/t5 families
first-class served families (they previously restored through the
registry but had no service):

- `model_cfg.json` (save/load_model_setup): a run-dir manifest holding
  the tokenizer descriptor + encoder config a combined/t5 checkpoint
  must be rebuilt with — written by `train-combined`, read by
  ModelRegistry, so serving and fleet co-serving never need the
  training CLI's --arch/--encoder/--max-length args re-supplied.
- `CombinedFrontend`: code -> (token ids, optional GraphSpec), the
  combined-family analog of RequestPreprocessor.
- `build_combined_service_parts`: the frontend+executor pair
  serve/server.py:ScoringService wires for a combined/t5 registry.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import time
from pathlib import Path
from typing import Any

import numpy as np

from deepdfa_tpu.core import config as config_mod
from deepdfa_tpu.eval import calibrate as calibrate_mod
from deepdfa_tpu.obs import metrics as obs_metrics, trace as obs_trace

logger = logging.getLogger(__name__)

#: the run-dir manifest that makes a combined/t5 run self-describing
MODEL_CFG_MANIFEST = "model_cfg.json"


# ---------------------------------------------------------------------------
# model_cfg.json: save/load the tokenizer + encoder setup


def save_model_setup(
    run_dir: str | Path,
    family: str,
    model_cfg: Any,
    tokenizer_desc: dict,
    max_length: int,
) -> Path:
    """Write the manifest a combined/t5 run needs to be restorable
    without CLI args. `tokenizer_desc` is {"kind": "hash", "vocab_size",
    "t5_frame"} or {"kind": "bpe", "vocab": path, "merges": path}."""
    d = dataclasses.asdict(model_cfg)
    encoder = d.pop("encoder")
    doc = {
        "family": family,
        "max_length": int(max_length),
        "tokenizer": dict(tokenizer_desc),
        "encoder": encoder,
        "model": d,
    }
    path = Path(run_dir) / MODEL_CFG_MANIFEST
    path.write_text(json.dumps(doc, indent=2))
    return path


def _build_tokenizer(desc: dict):
    from deepdfa_tpu.data.tokenizer import BpeTokenizer, HashTokenizer

    kind = desc.get("kind", "hash")
    if kind == "hash":
        return HashTokenizer(
            vocab_size=int(desc.get("vocab_size", 4096)),
            t5_frame=bool(desc.get("t5_frame", False)),
        )
    if kind == "bpe":
        return BpeTokenizer(Path(desc["vocab"]), Path(desc["merges"]))
    raise ValueError(f"unknown tokenizer kind {kind!r} in manifest")


def load_model_setup(run_dir: str | Path, family: str):
    """(tokenizer, model_cfg, max_length) from the run's manifest;
    raises FileNotFoundError/ValueError with operator-grade messages."""
    path = Path(run_dir) / MODEL_CFG_MANIFEST
    doc = json.loads(path.read_text())
    saved_family = doc.get("family")
    if saved_family != family:
        raise ValueError(
            f"{path} describes family {saved_family!r}, not {family!r} "
            f"— the run was trained with a different arch"
        )
    tok = _build_tokenizer(doc["tokenizer"])
    if family == "t5":
        from deepdfa_tpu.models import t5 as t5m

        enc = t5m.T5Config(**doc["encoder"])
        mcfg = t5m.DefectConfig(encoder=enc, **doc["model"])
    else:
        from deepdfa_tpu.models import combined as cmb
        from deepdfa_tpu.models.transformer import TransformerConfig

        enc = TransformerConfig(**doc["encoder"])
        mcfg = cmb.CombinedConfig(encoder=enc, **doc["model"])
    return tok, mcfg, int(doc["max_length"])


def try_load_model_setup(run_dir: str | Path, family: str):
    """load_model_setup, or None when no manifest exists (the caller
    decides whether that is an error)."""
    if not (Path(run_dir) / MODEL_CFG_MANIFEST).exists():
        return None
    return load_model_setup(run_dir, family)


# ---------------------------------------------------------------------------
# combined-family request frontend


@dataclasses.dataclass(frozen=True)
class TextFeatures:
    """The combined-family analog of serve/frontend.py:Features: `spec`
    is the CombinedExecutor payload (token ids, optional GraphSpec)."""

    spec: tuple
    node_lines: None = None


class CombinedFrontend:
    """code -> (token ids, GraphSpec | None), quacking like
    RequestPreprocessor for ScoringService (`features_full` /
    `features` / `cache` / `close`).

    When the model was trained use_graph=True the graph half routes
    through a real RequestPreprocessor (shared content-keyed cache); a
    function the graph frontend cannot parse degrades to a text-only
    row (has_graph=False) — deterministically, so batched and singleton
    scores still agree."""

    def __init__(self, tokenizer, max_length: int, graph_frontend=None):
        self.tok = tokenizer
        self.max_length = int(max_length)
        self.graph_frontend = graph_frontend
        self.cache = (
            graph_frontend.cache if graph_frontend is not None else {}
        )

    def features_full(self, code: str, request_id: int = -1) -> TextFeatures:
        ids = self.tok.encode(code, max_length=self.max_length)
        spec = None
        if self.graph_frontend is not None:
            from deepdfa_tpu.serve.frontend import FrontendError

            try:
                spec = self.graph_frontend.features(code, request_id)
            except FrontendError:
                spec = None  # text-only row, consistently
        return TextFeatures(spec=(np.asarray(ids, np.int32), spec))

    def features(self, code: str, request_id: int = -1):
        return self.features_full(code, request_id).spec

    def close(self) -> None:
        if self.graph_frontend is not None:
            self.graph_frontend.close()


def build_combined_service_parts(
    registry, cfg, node_budget: int, edge_budget: int,
    seq_buckets=None,
):
    """(frontend, executor) for a combined/t5 registry — the
    family-dispatch half of ScoringService.__init__.

    seq_buckets: explicit bucket edges replacing cfg.data.seq_buckets —
    the tuned layout (deepdfa_tpu/tune/, docs/tuning.md) fitted to the
    observed token-length distribution; passed by ScoringService so the
    registry's config digest (hot-swap admission) never sees it."""
    from deepdfa_tpu.serve import frontend as serve_frontend
    from deepdfa_tpu.serve.batcher import CombinedExecutor
    from deepdfa_tpu.serve.frontend import RequestPreprocessor

    tok = registry.tokenizer
    mcfg = registry.model_cfg
    if tok is None:
        from deepdfa_tpu.serve.registry import RegistryError

        raise RegistryError(
            f"serving family {registry.family!r} needs the run's "
            f"tokenizer: save a {MODEL_CFG_MANIFEST} manifest "
            f"(train-combined writes one) in {registry.run_dir}"
        )
    max_length = int(registry.serve_max_length or 0)
    if seq_buckets and max_length:
        # a tuned edge set must fit THIS registry's encoder capacity:
        # edges past max_length would warm programs beyond the
        # positional table the checkpoint was trained at (the tuned
        # record may have been fitted against a longer config), and the
        # top edge must still hold a full-length row — drop the
        # overflow and keep the capacity as the top edge (the
        # data.seq_buckets CLI contract; tuned edges refine only the
        # interior)
        seq_buckets = tuple(
            int(b) for b in seq_buckets if int(b) < max_length
        ) + (max_length,)
    buckets = tuple(
        int(b) for b in (seq_buckets or cfg.data.seq_buckets)
    ) or ((max_length,) if max_length else ())
    graph_fe = None
    if getattr(mcfg, "use_graph", False):
        graph_fe = RequestPreprocessor(
            cfg, registry.vocabs,
            use_joern=cfg.serve.use_joern,
            cache=serve_frontend.shared_cache(
                cfg.serve.feature_cache_entries
            ),
        )
    frontend = CombinedFrontend(
        tok, max_length or buckets[-1], graph_frontend=graph_fe
    )
    executor = CombinedExecutor(
        mcfg, registry.params, tok,
        seq_buckets=buckets,
        token_budget=cfg.data.token_budget,
        node_budget=node_budget, edge_budget=edge_budget,
        is_t5=(registry.family == "t5"),
        params_transform=registry.params_transform,
        mesh=getattr(registry, "mesh", None),
    )
    return frontend, executor


# ---------------------------------------------------------------------------
# the cascade itself


class CascadeStage2:
    """The escalation half of a cascade-mode ScoringService: a full
    stage-2 serving stack (registry + frontend + batcher, its own AOT
    warmup ladder) plus the band/temperature/shed policy."""

    def __init__(
        self,
        service,
        band: tuple[float, float],
        temperature: float = 1.0,
        shed_depth_fraction: float = 0.75,
        timeout_s: float = 60.0,
    ):
        self.service = service
        self.band = (float(band[0]), float(band[1]))
        self.temperature = float(temperature)
        self.shed_depth_fraction = float(shed_depth_fraction)
        self.timeout_s = float(timeout_s)
        r = obs_metrics.REGISTRY
        self._m_requests = r.counter("serve/cascade_requests")
        self._m_escalations = r.counter("serve/cascade_escalations")
        self._m_sheds = r.counter("serve/cascade_sheds")
        self._m_failures = r.counter("serve/cascade_failures")
        self._m_rate = r.gauge("serve/cascade_escalation_rate")
        self._m_stage2_s = r.histogram("serve/cascade_stage2_seconds")

    @classmethod
    def from_config(cls, cfg, run_dir):
        """Build the stage-2 stack per the primary serve config.
        serve.cascade is forced OFF on the stage-2 config — the stage-2
        service must never build a stage 3."""
        from deepdfa_tpu.serve.registry import (
            ModelRegistry,
            load_run_config,
        )
        from deepdfa_tpu.serve.server import ScoringService

        scfg = cfg.serve
        stage2_dir = Path(scfg.cascade_run_dir or run_dir)
        s2cfg = (
            cfg if stage2_dir == Path(run_dir)
            else load_run_config(stage2_dir)
        )
        s2cfg = config_mod.apply_overrides(s2cfg, [
            "serve.cascade=false",
            "serve.lines=false",
            "serve.request_log=false",
            "serve.hot_swap=false",
        ])
        from deepdfa_tpu.serve.registry import serve_mesh

        registry = ModelRegistry(
            stage2_dir,
            family=scfg.cascade_family,
            checkpoint=scfg.cascade_checkpoint,
            cfg=s2cfg,
            mesh=serve_mesh(s2cfg),
        )
        return cls(
            ScoringService(registry, s2cfg),
            band=tuple(scfg.cascade_band),
            temperature=scfg.cascade_temperature,
            shed_depth_fraction=scfg.cascade_shed_depth_fraction,
            timeout_s=scfg.cascade_timeout_s,
        )

    # -- policy ---------------------------------------------------------------

    def calibrated(self, prob: float) -> float:
        return float(
            calibrate_mod.temperature_scale([prob], self.temperature)[0]
        )

    def should_escalate(self, calibrated_prob: float) -> bool:
        return calibrate_mod.in_band(calibrated_prob, self.band)

    def overloaded(self) -> bool:
        """The service-level cascade shed (docs/cascade.md shed order):
        stage-2 queue past the depth fraction => new escalations answer
        with their stage-1 score instead of queueing."""
        depth = self.service.batcher.stats()["queue_depth"]
        limit = self.service.cfg.serve.queue_limit
        return depth >= self.shed_depth_fraction * limit

    def _publish_rate(self) -> None:
        n = self._m_requests.value
        if n:
            self._m_rate.set(self._m_escalations.value / n)

    # -- the shared verdict + accounting (online AND offline drives) ----------

    def screen(self, prob1: float) -> tuple[bool, dict]:
        """The stage-1 verdict BOTH drive paths share: count the
        request, calibrate, apply the band + the shed check. Returns
        (escalate?, response/log fields) — the caller performs the
        escalation and reports its outcome via note_escalated /
        note_escalation_failed, so counter semantics cannot drift
        between the HTTP handler and score_texts."""
        self._m_requests.inc()
        cal = self.calibrated(prob1)
        fields: dict = {
            "stage": 1,
            "stage1_prob": float(prob1),
            "calibrated_prob": round(cal, 6),
        }
        if self.should_escalate(cal):
            if self.overloaded():
                self._m_sheds.inc()
                fields["cascade_shed"] = 1
            else:
                return True, fields
        self._publish_rate()
        return False, fields

    def note_escalated(self, seconds: float) -> None:
        """One SUCCESSFUL stage-2 pass (escalations count successes
        only — a failed pass degrades to stage 1 and must not move the
        escalation rate the serve smoke pins against stage verdicts)."""
        self._m_escalations.inc()
        self._m_stage2_s.observe(seconds)
        self._publish_rate()

    def note_escalation_failed(self) -> None:
        self._m_failures.inc()
        self._publish_rate()

    # -- escalation -----------------------------------------------------------

    def escalate(self, code: str, request_id: str | None = None):
        """(stage-2 prob, seconds) for ONE request — the online path
        (HTTP handler threads co-batch through the stage-2 batcher)."""
        t0 = time.perf_counter()
        req = self.service.submit_code(code, request_id=request_id)
        prob = req.wait(self.timeout_s)
        return float(prob), time.perf_counter() - t0

    def decide(self, code: str, prob1: float, request_id: str | None = None):
        """The per-request cascade verdict: (final prob, response
        fields, extra SLO stage seconds). A stage-2 failure (timeout,
        queue full, executor error) DEGRADES to the stage-1 score —
        the screen already answered; losing the request to a stage-2
        hiccup would invert the cascade's whole degradation story
        (docs/cascade.md shed order; the offline drive does the same)."""
        escalate, info = self.screen(prob1)
        extra: dict = {}
        if escalate:
            try:
                with obs_trace.span(
                    "cascade_stage2", cat="serve", request_id=request_id
                ):
                    prob2, dt = self.escalate(code, request_id)
            except Exception:  # noqa: BLE001 - degrade, never fail
                logger.warning(
                    "stage-2 escalation failed for %s; serving the "
                    "stage-1 score", request_id, exc_info=True,
                )
                self.note_escalation_failed()
                info["cascade_failed"] = 1
            else:
                self.note_escalated(dt)
                info["stage"] = 2
                extra["cascade_stage2"] = dt
                return prob2, info, extra
        return float(prob1), info, extra

    def escalate_many(self, codes: list[str], request_ids=None):
        """Offline escalation drive (score_texts): every escalated
        request groups through the stage-2 batcher's deterministic
        score_all path. [(prob | None, seconds)] aligned with codes;
        None = a failed pass (counted via note_escalation_failed, the
        caller degrades that row to its stage-1 score)."""
        svc = self.service
        payloads = [svc.frontend.features_full(c).spec for c in codes]
        t0 = time.perf_counter()
        reqs = svc.batcher.score_all(payloads, request_ids=request_ids)
        out = []
        for req in reqs:
            try:
                prob = req.wait(self.timeout_s)
            except Exception:  # noqa: BLE001 - per-row fault isolation
                self.note_escalation_failed()
                out.append((None, req.latency_s or 0.0))
                continue
            dt = req.latency_s if req.latency_s is not None else (
                time.perf_counter() - t0
            )
            self.note_escalated(dt)
            out.append((float(prob), dt))
        return out

    # -- service plumbing -----------------------------------------------------

    def counters(self) -> dict:
        n = self._m_requests.value
        return {
            "requests": n,
            "escalations": self._m_escalations.value,
            "sheds": self._m_sheds.value,
            "failures": self._m_failures.value,
            "escalation_rate": (
                round(self._m_escalations.value / n, 4) if n else 0.0
            ),
        }

    def info(self) -> dict:
        """The /healthz cascade section."""
        reg = self.service.registry
        return {
            "band": list(self.band),
            "temperature": self.temperature,
            "shed_depth_fraction": self.shed_depth_fraction,
            "stage2_family": reg.family,
            "stage2_checkpoint": reg.checkpoint,
            "stage2_quantized": reg.quant_mode,
            "stage2_warmed_signatures": [
                list(s) for s in self.service.executor.signatures()
            ],
            "stage2_steady_state_recompiles": (
                self.service.steady_state_recompiles()
            ),
            **self.counters(),
        }

    def jit_lowerings(self) -> int:
        return self.service._jit_lowerings()

    def start(self) -> None:
        self.service.start()

    def close(self) -> None:
        self.service.close()


# ---------------------------------------------------------------------------
# smoke/test fixtures: a real stage-2 checkpoint without a training loop


def build_stage2_smoke(
    run_dir: str | Path,
    cfg,
    family: str = "combined",
    hidden: int = 8,
    layers: int = 1,
    heads: int = 2,
    max_length: int = 32,
    vocab_size: int = 256,
    use_graph: bool = False,
    seed: int = 0,
):
    """Lay down REAL stage-2 artifacts next to a (smoke) run's GGNN
    checkpoint: checkpoints-combined/ with a `best` tag and the
    model_cfg.json manifest — so cascade smokes and tests exercise the
    real registry restore path, not a mock. Returns (tokenizer,
    model_cfg)."""
    import jax

    from deepdfa_tpu.data.tokenizer import HashTokenizer
    from deepdfa_tpu.train.checkpoint import CheckpointManager

    run_dir = Path(run_dir)
    tok = HashTokenizer(
        vocab_size=vocab_size, t5_frame=(family == "t5")
    )
    if family == "t5":
        from deepdfa_tpu.models import t5 as t5m

        enc = t5m.T5Config.tiny(
            vocab_size=tok.vocab_size, hidden_size=2 * hidden,
            num_layers=layers, num_heads=heads, head_dim=hidden,
            ffn_size=4 * hidden,
        )
        enc = dataclasses.replace(enc, max_sequence_length=max_length)
        mcfg = t5m.DefectConfig(
            encoder=enc,
            graph_hidden_dim=cfg.model.hidden_dim,
            graph_input_dim=cfg.data.feat.input_dim,
            use_graph=use_graph,
        )
        params = t5m.init_defect_params(mcfg, jax.random.key(seed))
    else:
        from deepdfa_tpu.models import combined as cmb
        from deepdfa_tpu.models.transformer import TransformerConfig

        enc = TransformerConfig.tiny(
            vocab_size=tok.vocab_size,
            max_position_embeddings=max_length + 4,
            num_layers=layers, num_heads=heads,
            hidden_size=2 * hidden, intermediate_size=4 * hidden,
        )
        mcfg = cmb.CombinedConfig(
            encoder=enc,
            graph_hidden_dim=cfg.model.hidden_dim,
            graph_input_dim=cfg.data.feat.input_dim,
            use_graph=use_graph,
        )
        params = cmb.init_params(mcfg, jax.random.key(seed))
    mgr = CheckpointManager(
        run_dir / "checkpoints-combined", monitor="val_loss"
    )
    mgr.save(
        "epoch-0001", jax.device_get(params), {"val_loss": 1.0}, step=1
    )
    save_model_setup(
        run_dir, family, mcfg,
        {"kind": "hash", "vocab_size": tok.vocab_size,
         "t5_frame": family == "t5"},
        max_length,
    )
    return tok, mcfg


def train_stage2_smoke(
    run_dir: str | Path,
    cfg,
    n_examples: int,
    vuln_rate: float = 0.5,
    seed: int = 0,
    hidden: int = 48,
    layers: int = 2,
    heads: int = 4,
    max_length: int = 128,
    vocab_size: int = 512,
    max_epochs: int = 8,
    rows: int = 16,
):
    """A TRAINED tiny stage-2 combined checkpoint over the same
    synthetic corpus a smoke run's GGNN trained on — what the cascade
    bench needs (an untrained stage 2 makes the accuracy half of the
    frontier meaningless). Text-only (use_graph=False), single-shard;
    writes checkpoints-combined/best + model_cfg.json. Returns
    (tokenizer, model_cfg)."""
    import jax
    import numpy as np_mod

    from deepdfa_tpu.core import MeshConfig, config as core_config
    from deepdfa_tpu.data import generate, to_examples
    from deepdfa_tpu.data.text import collate_shards
    from deepdfa_tpu.data.tokenizer import HashTokenizer
    from deepdfa_tpu.models import combined as cmb
    from deepdfa_tpu.models.transformer import TransformerConfig
    from deepdfa_tpu.parallel import make_mesh
    from deepdfa_tpu.train.combined_loop import CombinedTrainer

    run_dir = Path(run_dir)
    examples = to_examples(
        generate(n_examples, vuln_rate=vuln_rate, seed=seed)
    )
    tok = HashTokenizer(vocab_size=vocab_size)
    token_ids = np_mod.stack([
        tok.encode(e.code, max_length=max_length) for e in examples
    ])
    labels = [int(e.label or 0) for e in examples]
    enc = TransformerConfig.tiny(
        vocab_size=tok.vocab_size,
        max_position_embeddings=max_length + 4,
        num_layers=layers, num_heads=heads,
        hidden_size=2 * hidden, intermediate_size=4 * hidden,
        dropout_rate=0.0,
    )
    mcfg = cmb.CombinedConfig(
        encoder=enc,
        graph_hidden_dim=cfg.model.hidden_dim,
        graph_input_dim=cfg.data.feat.input_dim,
        use_graph=False,
    )
    tcfg = core_config.apply_overrides(cfg, [
        f"train.max_epochs={int(max_epochs)}",
        "train.optim.learning_rate=0.001",
        "train.optim.warmup_frac=0.1",
        "train.optim.grad_clip_norm=1.0",
    ])
    mesh = make_mesh(MeshConfig(dp=1), devices=jax.devices()[:1])
    steps_per_epoch = max(1, n_examples // rows)
    trainer = CombinedTrainer(
        tcfg, mcfg, mesh=mesh,
        total_steps=steps_per_epoch * int(max_epochs),
    )

    def batches(_epoch=0):
        out = []
        for k in range(0, n_examples - n_examples % rows, rows):
            sel = list(range(k, k + rows))
            out.append(collate_shards(
                token_ids[sel], [labels[i] for i in sel], sel, {},
                num_shards=1, rows_per_shard=rows,
                node_budget=512, edge_budget=2048, pad_id=tok.pad_id,
            ))
        return out

    ckpts = trainer.make_checkpoints(run_dir / "checkpoints-combined")
    state = trainer.init_state()
    trainer.fit(state, batches, val_batches=batches, checkpoints=ckpts)
    save_model_setup(
        run_dir, "combined", mcfg,
        {"kind": "hash", "vocab_size": tok.vocab_size,
         "t5_frame": False},
        max_length,
    )
    return tok, mcfg


# ---------------------------------------------------------------------------
# cascade-mode serve_log validation (scripts/check_obs_schema.py
# --cascade-log)


def validate_cascade_log(path: str | Path) -> dict:
    """Structural + schema validation of a cascade-mode serve_log.jsonl:
    the summary record carries the cascade section (escalation fields
    present), per-request entries declare their deciding `stage` (and
    escalated ones their cascade_stage2_ms), the SLO snapshot declares
    the cascade stages, and every flattened scalar tag is declared in
    obs/metrics.py:SCHEMA."""
    path = Path(path)
    problems: list[str] = []
    try:
        lines = path.read_text().splitlines()
    except OSError as e:
        return {"ok": False, "problems": [f"unreadable: {e}"]}
    records: list[dict] = []
    n_requests = n_escalated = n_summaries = 0
    saw_cascade_section = saw_stage_windows = False
    for lineno, line in enumerate(lines, 1):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError as e:
            problems.append(f"line {lineno}: not JSON ({e})")
            continue
        if not isinstance(rec, dict):
            problems.append(f"line {lineno}: not an object")
            continue
        records.append(rec)
        if "request" in rec:
            req = rec["request"]
            if not isinstance(req, dict):
                problems.append(f"line {lineno}: request not an object")
                continue
            if int(req.get("status", 0)) != 200:
                continue  # sheds/rejects carry no stage verdict
            n_requests += 1
            if "stage" not in req:
                problems.append(
                    f"line {lineno}: 200 request entry missing the "
                    f"cascade `stage` field"
                )
            elif int(req["stage"]) == 2:
                n_escalated += 1
                if "cascade_stage2_ms" not in req:
                    problems.append(
                        f"line {lineno}: escalated request missing "
                        f"cascade_stage2_ms"
                    )
        elif "serve" in rec or "serve_slo" in rec:
            n_summaries += 1
            casc = rec.get("cascade")
            if isinstance(casc, dict):
                missing = [
                    k for k in ("requests", "escalations",
                                "escalation_rate")
                    if k not in casc
                ]
                if missing:
                    problems.append(
                        f"line {lineno}: cascade section missing "
                        f"{missing}"
                    )
                else:
                    saw_cascade_section = True
            slo = rec.get("serve_slo")
            if isinstance(slo, dict):
                for view in slo.values():
                    if isinstance(view, dict) and "cascade_stage1" in (
                        view.get("latency_ms") or {}
                    ):
                        saw_stage_windows = True
    if not saw_cascade_section:
        problems.append(
            "no summary record carries a complete cascade section "
            "(was the log produced with serve.cascade=true?)"
        )
    if n_requests and not saw_stage_windows:
        problems.append(
            "no SLO window carries cascade_stage1 latency — the engine "
            "was not built with the cascade stages"
        )
    undeclared = obs_metrics.undeclared_tags(records)
    for tag in undeclared:
        problems.append(f"undeclared metrics tag: {tag}")
    return {
        "ok": not problems,
        "records": len(records),
        "requests": n_requests,
        "escalated": n_escalated,
        "summaries": n_summaries,
        "undeclared": undeclared,
        "problems": problems,
    }
