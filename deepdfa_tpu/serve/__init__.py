"""Online inference subsystem (docs/serving.md).

- `serve.registry`  — checkpoint -> inference-only model handle
  (params, no optimizer), config/vocab digest pinned, hot-swappable.
- `serve.batcher`   — bounded-queue dynamic batcher over AOT-warmed
  per-signature bucket executables (zero steady-state lowerings).
- `serve.frontend`  — cached request preprocessing (built-in parser or
  a pooled Joern session) into the training feature path.
- `serve.server`    — stdlib HTTP endpoint (/score, /healthz, /stats)
  + the offline batch scorer the `score` CLI drives.
- `serve.quant`     — post-training int8 serving executables
  (`tag@int8` registry entries, pinned calibration drift bound).
- `serve.cascade`   — two-stage cascaded inference (GGNN screen ->
  combined/t5 escalation) + combined-family serving support.

Everything is reachable only through `cfg.serve` and the `serve`/`score`
CLI commands — training paths never import this package.
"""

from deepdfa_tpu.serve.batcher import (
    CombinedExecutor,
    DynamicBatcher,
    GgnnExecutor,
    QueueFull,
    RequestTooLarge,
    ScoreRequest,
)
from deepdfa_tpu.serve.frontend import (
    FeatureCache,
    FrontendError,
    RequestPreprocessor,
    SessionPool,
)
from deepdfa_tpu.serve.cascade import (
    CascadeStage2,
    CombinedFrontend,
    validate_cascade_log,
)
from deepdfa_tpu.serve.quant import (
    QuantizationError,
    dequantize_params,
    quantize_params,
)
from deepdfa_tpu.serve.registry import (
    ModelRegistry,
    RegistryError,
    config_digest,
    load_vocabs,
)
from deepdfa_tpu.serve.server import (
    BackgroundServer,
    ScoringService,
    make_server,
    score_texts,
    serve_forever,
    write_serve_log,
)

__all__ = [
    "CombinedExecutor",
    "DynamicBatcher",
    "GgnnExecutor",
    "QueueFull",
    "RequestTooLarge",
    "ScoreRequest",
    "FeatureCache",
    "FrontendError",
    "RequestPreprocessor",
    "SessionPool",
    "CascadeStage2",
    "CombinedFrontend",
    "validate_cascade_log",
    "QuantizationError",
    "dequantize_params",
    "quantize_params",
    "ModelRegistry",
    "RegistryError",
    "config_digest",
    "load_vocabs",
    "BackgroundServer",
    "ScoringService",
    "make_server",
    "score_texts",
    "serve_forever",
    "write_serve_log",
]
