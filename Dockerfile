# Container image for deepdfa_tpu — role parity with the reference's
# Dockerfile (conda env + PYTHONPATH setup for DDFA/LineVul/CodeT5).
#
# The TPU runtime ships in the `jax[tpu]` extra; on GKE/GCE TPU VMs the
# libtpu driver comes from the host image, so the container only needs the
# Python stack. CPU-only usage (preprocessing fan-out, CI) works with
# plain `jax`.
FROM python:3.12-slim

RUN apt-get update && apt-get install -y --no-install-recommends \
        git g++ make \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /app
COPY pyproject.toml ./
COPY deepdfa_tpu ./deepdfa_tpu
COPY configs ./configs
COPY scripts ./scripts

# TPU hosts: pip install "jax[tpu]" -f https://storage.googleapis.com/jax-releases/libtpu_releases.html
RUN pip install --no-cache-dir \
        jax flax optax orbax-checkpoint chex einops numpy pandas pytest \
    && pip install --no-cache-dir -e . --no-deps

# artifact storage mounts here (DEEPDFA_TPU_STORAGE redirect)
ENV DEEPDFA_TPU_STORAGE=/storage
VOLUME /storage

ENTRYPOINT ["python", "-m", "deepdfa_tpu.cli"]
CMD ["--help"]
